"""Delta-debugging shrinker for failing batched-sim runs.

When a guided (or matrix) run fails, the interesting part is rarely
the whole four-cycle fault schedule — it is usually one window that
opens at the wrong moment. This module minimizes a failing run to the
smallest explicit nemesis schedule (and the shortest op stream) that
still reproduces the SAME verdict signature, and persists the result
as a ``shrink.json`` store artifact next to ``results.json``.

Mechanics:

- Schedules are delta-debugged with classic ddmin over window lists.
  Every candidate re-executes under same-seed sim determinism, and a
  whole ddmin round's candidate population runs through ONE
  ``simbatch.generate`` call: the failing seed repeats across lanes
  with a different per-seed ``nem_schedules`` entry each (the engine's
  nemesis arrays are per-seed already, so this is free batching).
- Acceptance is by verdict signature equality only — the workload
  checker re-runs over each candidate history and the candidate is
  kept iff ``_failure_signature`` matches the original failure. Op
  counts, timings and exact violation sites may differ; the *bug
  class* may not.
- The op stream shrinks after the schedule: halving ``ops_per_lane``
  redraws every client plane (draw shapes are part of the epoch), so
  those candidates cannot share a generate() call and run singly.

The artifact embeds the minimized :class:`BatchConfig` verbatim
(``config`` key) plus a ``repro`` command line, so
``python -m jepsen_etcd_tpu replay <dir>/shrink.json`` re-executes and
re-checks it without depending on the opts→config mapping.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ..simbatch import BatchConfig, default_schedule, generate
from . import telemetry

#: nemesis ops emitted per schedule window (start/stop invoke + :info)
OPS_PER_WINDOW = 4

#: give up on a shrink after this many candidate executions
MAX_EXECUTIONS = 256


def _checker_for(config: BatchConfig, checker_opts: dict):
    from ..workloads import workloads
    return workloads()[config.workload](dict(checker_opts))["checker"]


def _signature(results: dict) -> str:
    from .store import failure_signature
    return failure_signature(results)


def checker_opts_from(opts: dict) -> dict:
    """The slice of run opts the workload checker factory needs."""
    nodes = opts.get("nodes") or ["n1", "n2", "n3"]
    out = {"nodes": list(nodes),
           "concurrency": int(opts.get("concurrency") or 2 * len(nodes))}
    # MVCC surface thresholds (checkers/mvcc.py reads them from the
    # test map at check time): shrink/replay verdicts must honor the
    # same bounds the original run was judged under
    for k in ("staleness_bound_s", "lease_ttl_ms", "compact_keep"):
        if opts.get(k) is not None:
            out[k] = opts[k]
    return out


def _eval_population(config, seed, scheds, checker, checker_opts):
    """Verdict signatures for a candidate-schedule population, one
    batched generate() call (same seed on every lane)."""
    tel = telemetry.current()
    g = generate(config, [seed] * len(scheds), nem_schedules=scheds)
    sigs = []
    for h in g["histories"]:
        res = checker.check(dict(checker_opts), h)
        sigs.append(_signature({"workload": res}))
    tel.counter("shrink.candidates", len(scheds))
    return sigs


def _ddmin_windows(config, seed, sched, sig0, checker, checker_opts,
                   budget):
    """Classic ddmin over the window list; each round's candidates are
    evaluated as one batched population. Returns (min_sched, rounds,
    executions)."""
    cur = list(sched)
    rounds = execs = 0
    n = 2
    while len(cur) >= 2 and execs < budget:
        rounds += 1
        size = len(cur) // n
        chunks = [cur[i:i + size] for i in range(0, len(cur), size)]
        # subsets first, then complements (ddmin order)
        cands = [c for c in chunks if 0 < len(c) < len(cur)]
        cands += [cur[:i * size] + cur[(i + 1) * size:]
                  for i in range(len(chunks))
                  if 0 < len(cur) - len(chunks[i]) < len(cur)]
        if not cands:
            break
        sigs = _eval_population(config, seed, cands, checker,
                                checker_opts)
        execs += len(cands)
        hit = next((i for i, sg in enumerate(sigs) if sg == sig0), None)
        if hit is not None:
            cur = list(cands[hit])
            n = 2
        elif n < len(cur):
            n = min(len(cur), 2 * n)
        else:
            break
    return cur, rounds, execs


def _shrink_ops(config, seed, sched, sig0, checker, checker_opts,
                budget):
    """Halve ops_per_lane while the signature survives; each candidate
    redraws the client planes so these run one-by-one."""
    tel = telemetry.current()
    cfg, execs = config, 0
    while cfg.ops_per_lane > 2 and execs < budget:
        cand = dict(cfg.to_dict(), ops_per_lane=cfg.ops_per_lane // 2,
                    nem_schedule=[list(w) for w in sched])
        c2 = BatchConfig(**cand)
        sg = _eval_population(c2, seed, [sched], checker,
                              checker_opts)[0]
        execs += 1
        if sg != sig0:
            break
        cfg = c2
    return cfg, execs


def shrink_run(opts: dict, seed: int, *, store_dir: Optional[str] = None,
               max_executions: int = MAX_EXECUTIONS) -> Optional[dict]:
    """Minimize a failing batched run; return the artifact dict (and
    write ``<store_dir>/shrink.json`` when a store dir is given).

    Returns None when there is nothing to shrink (no faults configured)
    or the failure does not reproduce as a workload-checker signature
    under re-execution (e.g. an infrastructure error)."""
    tel = telemetry.current()
    config = BatchConfig.from_opts(opts)
    seed = int(seed)
    if not config.nemeses:
        return None
    sched = [tuple(w) for w in (config.nem_schedule
                                or default_schedule(config, seed))]
    checker_opts = checker_opts_from(opts)
    checker = _checker_for(config, checker_opts)
    tel.counter("shrink.runs")
    sig0 = _eval_population(config, seed, [sched], checker,
                            checker_opts)[0]
    if not sig0:
        tel.counter("shrink.irreproducible")
        return None
    min_sched, rounds, execs = _ddmin_windows(
        config, seed, sched, sig0, checker, checker_opts,
        max_executions)
    tel.counter("shrink.rounds", rounds)
    min_cfg = BatchConfig(**dict(
        config.to_dict(), nem_schedule=[list(w) for w in min_sched]))
    min_cfg, oexecs = _shrink_ops(min_cfg, seed, min_sched, sig0,
                                  checker, checker_opts,
                                  max(0, max_executions - execs - 1))
    if len(min_sched) < len(sched):
        tel.counter("shrink.accepted")
    art = {
        "schema": 1,
        "workload": config.workload,
        "seed": seed,
        "signature": sig0,
        "checker_opts": checker_opts,
        "config": min_cfg.to_dict(),
        "original_windows": len(sched),
        "windows": len(min_sched),
        "nemesis_ops": OPS_PER_WINDOW * len(min_sched),
        "ops_per_lane": {"original": config.ops_per_lane,
                         "min": min_cfg.ops_per_lane},
        "rounds": rounds,
        "executions": 1 + execs + oexecs,
    }
    if store_dir:
        path = os.path.join(store_dir, "shrink.json")
        art["repro"] = f"python -m jepsen_etcd_tpu replay {path}"
        with open(path, "w") as f:
            json.dump(art, f, indent=1, sort_keys=True)
        tel.counter("shrink.artifacts")
        try:  # surface the artifact on the dashboard immediately
            from .store_index import record_shrink
            record_shrink(store_dir)
        except Exception:
            pass
    else:
        art["repro"] = "python -m jepsen_etcd_tpu replay <shrink.json>"
    return art


def replay_artifact(path: str) -> dict:
    """Re-execute a ``shrink.json`` artifact and re-check it; returns
    ``{"signature", "match", "valid?", "windows", "nemesis_ops"}``.
    ``match`` is True iff the minimized schedule still reproduces the
    recorded verdict signature."""
    with open(path) as f:
        art = json.load(f)
    config = BatchConfig(**art["config"])
    checker = _checker_for(config, art["checker_opts"])
    g = generate(config, [int(art["seed"])])
    res = checker.check(dict(art["checker_opts"]), g["histories"][0])
    sig = _signature({"workload": res})
    return {
        "signature": sig,
        "expected": art["signature"],
        "match": sig == art["signature"],
        "valid?": bool(res.get("valid?")),
        "windows": art.get("windows"),
        "nemesis_ops": art.get("nemesis_ops"),
        "seed": art.get("seed"),
        "workload": art.get("workload"),
    }
