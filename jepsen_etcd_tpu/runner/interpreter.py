"""The generator interpreter: schedules generator ops onto worker tasks.

The analog of jepsen.generator.interpreter (SURVEY §3.1 "HOT LOOP #1"),
re-designed for the virtual-time runtime.

Design (mirrors jepsen's): a coordinator coroutine polls the generator
(committed-poll protocol, see generators/core.py) and *immediately*
dispatches each op to its thread's worker inbox, marking the thread busy —
even ops whose :time is in the future (the worker sleeps until then). This
keeps a far-future op (e.g. a staggered nemesis op) from blocking other
threads' dispatch. Workers send invoke/completion events back on a single
queue; the coordinator records them in arrival (= virtual-time) order and
feeds them to generator.update.

Process semantics mirror jepsen: thread t starts as process t; when an op
completes as :info (indefinite — the worker may still hold resources), the
process is retired and replaced by process + concurrency, so thread =
process mod concurrency (cf. reference watch.clj:281-282).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..core.op import Op, NEMESIS, INFO
from ..core.history import History, ColumnsBuilder
from ..generators.core import Context, ensure_gen, PENDING, _WorkersMap
from .sim import Future, SimLoop, Queue, current_loop

import logging

logger = logging.getLogger("jepsen_etcd_tpu.run")


async def interpret(
    test: Any,
    gen: Any,
    invoke: Callable,  # async (process, op) -> completed Op
    concurrency: int,
    nemesis_invoke: Optional[Callable] = None,  # async (op) -> completed Op
    loop: Optional[SimLoop] = None,
    on_op: Optional[Callable] = None,  # observer: called with each recorded op
    stream: Optional[Any] = None,  # runner.stream.StreamFeed (chunk drain)
) -> History:
    """Run a generator to exhaustion; returns the recorded history."""
    loop = loop or current_loop()
    gen = ensure_gen(gen)

    threads: list = list(range(concurrency)) + (
        [NEMESIS] if nemesis_invoke is not None else [])
    workers = {t: t for t in threads}
    free = set(threads)
    outstanding = {t: 0 for t in threads}  # dispatched, not yet completed
    inboxes = {t: Queue(loop) for t in threads}
    events: Queue = Queue(loop)  # ("invoke"|"complete", thread, op)
    history: list[Op] = []
    index = [0]
    # SoA columns emitted alongside the dict stream: one row per event,
    # so checkers can consume typed arrays with no per-op dict access
    # (core/history.py OpColumns; schema in OBSERVABILITY.md §columns)
    columns = ColumnsBuilder()
    col_append = columns.append
    # streaming check feed: the builder hands chunks to a checker
    # worker while generation proceeds (runner/stream.py)
    if stream is not None:
        stream.attach(columns)
    stream_tick = stream.on_record if stream is not None else None

    def record(op: Op) -> Op:
        op = Op(op)  # evolve() unrolled: one copy, two direct stores
        op["index"] = index[0]
        op["time"] = loop.now
        index[0] += 1
        history.append(op)
        col_append(op)
        if stream_tick is not None:
            stream_tick()
        if on_op is not None:
            on_op(op)
        return op

    # Snapshots shared across polls until the underlying sets mutate: ctx()
    # runs several times per op, and restrict() memoizes sub-contexts on the
    # Context instance (see generators.core.Context).  The Context itself is
    # cached too — across polls only virtual time moves, which set_time()
    # propagates in place — so the restrict() memo survives between polls.
    snap: dict = {"workers": None, "free": None, "ctx": None}

    def ctx() -> Context:
        c = snap["ctx"]
        if c is not None:
            c.set_time(loop.now)
            return c
        if snap["workers"] is None:
            snap["workers"] = _WorkersMap(workers)
        if snap["free"] is None:
            snap["free"] = frozenset(free)
        c = Context(time=loop.now, free=snap["free"],
                    workers=snap["workers"], rng=loop.rng,
                    concurrency=concurrency)
        snap["ctx"] = c
        return c

    async def worker(thread: Any) -> None:
        while True:
            op = await inboxes[thread].get()
            if op is None:
                return
            if op["time"] > loop.now:
                await loop.sleep(op["time"] - loop.now)
            p = workers[thread]
            if op.get("process") != p:
                op = op.evolve(process=p)
            events.put(("invoke", thread, op))
            try:
                if thread == NEMESIS:
                    done = await nemesis_invoke(op)
                else:
                    done = await invoke(workers[thread], op)
            except Exception as e:  # a worker crash is an indefinite op
                logger.exception("worker %r crashed on %r", thread, op)
                done = op.evolve(type=INFO, error=("worker-crash", repr(e)))
            done = Op(done)
            # Retire the process *here*, before we could dequeue a queued
            # next op: an :info process must never invoke again
            # (jepsen semantics; the coordinator may handle this event
            # only after we've already picked up the next op).
            if done.get("type") == INFO and isinstance(thread, int):
                workers[thread] = workers[thread] + concurrency
                snap["workers"] = None
                snap["ctx"] = None
            events.put(("complete", thread, done))

    tasks = [loop.spawn(worker(t), name=f"worker-{t}") for t in threads]

    def handle(kind: str, thread: Any, op: Op) -> None:
        nonlocal gen
        op = record(op)
        if kind == "complete":
            outstanding[thread] -= 1
            if outstanding[thread] == 0:
                free.add(thread)
                snap["free"] = None
                snap["ctx"] = None
        if gen is not None:
            gen = gen.update(test, ctx(), op)

    _DEADLINE = object()  # sentinel: next_event gave up waiting

    async def next_event(deadline: Optional[int] = None) -> None:
        """Handle one event; give up at deadline (virtual time) if given.

        The deadline path used to be ``wait_for(spawn(events.get()),
        dt)`` — a Task + coroutine + 2 Futures per poll, twice per op in
        rate-0 runs.  This open-codes the same dance with two plain
        bounce callbacks.  The bounces are not an accident: they
        reproduce the old shape's scheduler hops (task wakeup, then
        wait_for's on_done) so every externally visible callback keeps
        its exact (time, seq) order relative to worker puts — histories
        stay bit-identical to the task-based implementation.
        """
        if deadline is None:
            kind, thread, op = await events.get()
        else:
            if loop.now >= deadline:
                return
            f = loop.future()       # the queue getter (was: evget's)
            gate = loop.future()    # what we actually await
            got_item = False        # ~ "the evget task completed"

            def hop1(fut) -> None:  # ~ evget task wakeup + step
                nonlocal got_item
                got_item = True
                loop._push_soon(hop2, (fut,))

            def hop2(fut) -> None:  # ~ wait_for's on_done
                timer.cancel()
                if not gate._state:
                    gate.set_result(fut._result)

            def on_timeout() -> None:
                if not gate._state:
                    gate.set_result(_DEADLINE)

            if len(events):
                # unreachable in practice (the main loop drains the queue
                # synchronously before polling), kept for safety
                kind, thread, op = await events.get()
                handle(kind, thread, op)
                return
            events._getters.append(f)
            f.add_done_callback(hop1)
            timer = loop.call_at(deadline, on_timeout)
            got = await gate
            if got is _DEADLINE:
                if got_item:
                    # delivery raced the deadline and won (the old code's
                    # "task.done despite timeout" branch): handle it
                    kind, thread, op = f._result
                    handle(kind, thread, op)
                    return
                # ~ task.cancel(): the stale getter is cleaned up one
                # scheduler hop later, with Queue.get's re-route semantics
                # for an item delivered into the window
                def cleanup() -> None:
                    if f in events._getters:
                        events._getters.remove(f)
                    elif f._state == Future.DONE:
                        if events._getters:
                            events._getters.popleft().set_result(f._result)
                        else:
                            events._items.appendleft(f._result)

                loop._push_soon(cleanup, ())
                return
            kind, thread, op = got
        handle(kind, thread, op)

    while True:
        # Drain any already-queued events first so ctx is fresh.
        while len(events):
            kind, thread, op = await events.get()
            handle(kind, thread, op)
        res = gen.op(test, ctx()) if gen is not None else None
        if res is None:
            if len(free) == len(threads):
                break
            await next_event()
            continue
        if res[0] == PENDING:
            _, wake, gen = res
            if wake is not None and wake > loop.now:
                await next_event(deadline=wake)
            else:
                await next_event()
            continue
        op, gen = res
        if op.get("type") == "log":
            logger.info("%s", op.get("value"))
            continue
        thread = op["process"] if not isinstance(op["process"], int) \
            else op["process"] % concurrency
        # The generator state for this op is already committed, so the op
        # must not be dropped: enqueue even onto a busy thread (the worker
        # drains its inbox sequentially); `free` stays false until the
        # inbox is empty again (see handle()).
        if thread in free:
            free.discard(thread)
            snap["free"] = None
            snap["ctx"] = None
        outstanding[thread] += 1
        inboxes[thread].put(op)

    for t in threads:
        inboxes[t].put(None)  # retire workers
    for t in tasks:
        await t
    return History(history, columns=columns.finish())
