from .sim import (
    SimLoop, Task, Future, Event, Queue, sleep, current_loop, Cancelled,
    wait_for, gather,
)

__all__ = [
    "SimLoop", "Task", "Future", "Event", "Queue", "sleep", "current_loop",
    "Cancelled", "wait_for", "gather",
]
