"""Framed socket transport shared by the checker service and the
campaign's host agents.

One wire shape, two socket families:

- a filesystem path         -> AF_UNIX stream socket (single host)
- ``tcp://HOST:PORT``       -> AF_INET stream socket (multi-host)

Frames are 8-byte little-endian length prefixes followed by the
payload (the format ``runner/checker_service.py`` has always spoken).
The length is validated against ``max_frame`` BEFORE any payload
allocation, so a corrupt or adversarial prefix can never balloon the
heap. EOF exactly on a frame boundary is a clean close (``None``);
EOF anywhere inside a frame — mid-header or mid-payload — raises
``TornFrame`` so readers can tell a peer that finished from a link
that died, which is the distinction the net/ fault plane trades in.

TCP connections open with a one-line text preamble::

    JET-HOST <name>\\n

naming the sending host. It serves two masters: the service reads it
for per-host counter attribution (``service.host_submitted.<host>``),
and the ``net/`` proxy plane's sniffer reads it to attribute the
connection, so a partition ``frozenset((host, "svc"))`` severs service
traffic exactly like SUT peer traffic. Unix-socket connections skip
the preamble (same-host, nothing to attribute).
"""

from __future__ import annotations

import socket
import struct
from typing import Optional, Tuple

_LEN = struct.Struct("<Q")

#: refuse frames past this size (a corrupt length prefix must not
#: allocate the heap): 1 GiB >> any real campaign's per-request packs
MAX_FRAME = 1 << 30

#: connection preamble on TCP transports: ``JET-HOST <name>\n`` — the
#: net/ proxy attributes on it, the service counts per-host on it
PREAMBLE = b"JET-HOST "

#: longest host name the preamble will carry (sanity cap so a garbage
#: stream can't make ``read_preamble`` buffer forever hunting for \n)
MAX_PREAMBLE = 256


class TornFrame(ValueError):
    """EOF inside a frame: the peer (or the link) died mid-message."""


def is_tcp(endpoint: str) -> bool:
    return isinstance(endpoint, str) and endpoint.startswith("tcp://")


def parse_tcp(endpoint: str) -> Tuple[str, int]:
    """``tcp://HOST:PORT`` -> (host, port)."""
    rest = endpoint[len("tcp://"):]
    host, _, port = rest.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"bad tcp endpoint {endpoint!r} "
                         "(want tcp://HOST:PORT)")
    return host, int(port)


def connect(endpoint: str, timeout: Optional[float] = None) -> socket.socket:
    """Open a stream socket to an endpoint (unix path or tcp:// URL)."""
    if is_tcp(endpoint):
        host, port = parse_tcp(endpoint)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.settimeout(timeout)
        s.connect((host, port))
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    else:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(timeout)
        s.connect(endpoint)
    return s


def listen_tcp(spec) -> Tuple[socket.socket, str]:
    """Bind a TCP listener from a spec (True -> loopback ephemeral,
    int -> loopback port, "HOST:PORT" -> explicit) and return
    ``(listener, "tcp://host:port")``."""
    host, port = "127.0.0.1", 0
    if spec is True or spec is None:
        pass
    elif isinstance(spec, int):
        port = spec
    elif isinstance(spec, str) and spec:
        if ":" in spec:
            h, _, p = spec.rpartition(":")
            host, port = (h or "127.0.0.1"), int(p)
        else:
            port = int(spec)
    ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    ls.bind((host, port))
    ls.listen(64)
    bhost, bport = ls.getsockname()[:2]
    return ls, f"tcp://{bhost}:{bport}"


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def send_preamble(sock: socket.socket, host: str) -> None:
    sock.sendall(PREAMBLE + host.encode() + b"\n")


class FrameReader:
    """Buffered, re-entrant frame reader for one socket.

    Re-entrant means a ``socket.timeout`` mid-frame leaves the partial
    bytes (and the already-parsed length) buffered, so the next call
    resumes exactly where it stopped — the client's heartbeat loop
    leans on this. ``recv_frame`` returns ``None`` only on EOF at a
    frame boundary; EOF inside a frame raises :class:`TornFrame`, and
    a length prefix past ``max_frame`` raises ``ValueError`` before a
    single payload byte is read or allocated.
    """

    def __init__(self, sock: socket.socket,
                 max_frame: int = MAX_FRAME) -> None:
        self._sock = sock
        self._buf = bytearray()
        self._need: Optional[int] = None  # parsed length of a pending frame
        self.max_frame = max_frame

    def _recv_exact(self, n: int) -> Optional[bytes]:
        """n buffered bytes; None on EOF with an EMPTY buffer (clean
        boundary), TornFrame on EOF with partial bytes."""
        while len(self._buf) < n:
            chunk = self._sock.recv(1 << 16)
            if not chunk:
                if not self._buf:
                    return None
                raise TornFrame(
                    f"EOF mid-read ({len(self._buf)}/{n} bytes)")
            self._buf += chunk
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    def read_preamble(self) -> Optional[str]:
        """Consume a ``JET-HOST <name>\\n`` preamble if the stream
        opens with one; returns the host name, or None (leaving the
        buffer untouched) when the first bytes are a frame instead."""
        k = len(PREAMBLE)
        while len(self._buf) < k:
            # stop early the moment the prefix diverges — a frame's
            # length header must not be held hostage to 9 bytes
            if self._buf and not PREAMBLE.startswith(bytes(self._buf)):
                return None
            chunk = self._sock.recv(1 << 16)
            if not chunk:
                return None
            self._buf += chunk
        if bytes(self._buf[:k]) != PREAMBLE:
            return None
        while b"\n" not in self._buf:
            if len(self._buf) > k + MAX_PREAMBLE:
                raise ValueError("unterminated JET-HOST preamble")
            chunk = self._sock.recv(1 << 16)
            if not chunk:
                raise TornFrame("EOF inside JET-HOST preamble")
            self._buf += chunk
        nl = self._buf.index(b"\n")
        host = bytes(self._buf[k:nl]).decode("utf-8", "replace").strip()
        del self._buf[:nl + 1]
        return host

    def recv_frame(self) -> Optional[bytes]:
        if self._need is None:
            head = self._recv_exact(_LEN.size)
            if head is None:
                return None
            (n,) = _LEN.unpack(head)
            if n > self.max_frame:
                raise ValueError(
                    f"frame of {n} bytes exceeds max_frame "
                    f"{self.max_frame}")
            self._need = n
        payload = self._recv_exact(self._need)
        if payload is None:
            raise TornFrame("EOF after frame header")
        self._need = None
        return payload
