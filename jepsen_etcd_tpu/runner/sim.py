"""A tiny deterministic virtual-time coroutine kernel.

The reference harness runs real JVM threads against a real cluster over
wall-clock time.  Our hermetic design replaces that with *virtual time*: all
concurrency (worker threads, nemesis, watch streams, lease expiry, raft
election timers) runs on this single-threaded, discrete-event scheduler.  A
10k-op history at 200 Hz spans 50 virtual seconds but executes in
milliseconds, and every run is exactly reproducible from its seed —
a capability the reference lacks (its histories are wall-clock
nondeterministic).

This is intentionally *not* asyncio: the scheduler must be deterministic
(heap ordered by (time, seq)), the clock must be virtual, and we need
precise control of cancellation for op timeouts (cf. reference
``client.clj:244-252`` — await with 5 s timeout -> indefinite result).

Generator-epoch ledger
----------------------
The same-instant ordering rule IS the determinism contract: the golden
hashes pin histories, and the hashes are only stable because the rule
below never changes silently. Changing how ties break — or anything
else that re-keys a same-seed history — requires declaring a NEW epoch
here, not editing an old one.

- **epoch-v1** (this module, SimLoop): events order by ``(time, seq)``
  — same-instant events run in push order, i.e. the order coroutines
  happened to schedule them. The single-run golden-hash bar
  (PERF.md §gen) pins epoch-v1 histories.
- **epoch-v2** (``simbatch/``, the lockstep batched generator): events
  order by ``(time, lane, seq)`` — same-instant events drain in
  ascending owning-lane order, push order only as the final tiebreak.
  The 16-seed golden-hash pin in tests/test_simbatch.py pins epoch-v2
  histories, and an epoch-v2 vs epoch-v1 fuzz checks
  *verdict* equality across workload × nemesis (histories differ
  op-by-op across epochs — that is the point of declaring an epoch —
  but checker verdicts must not).
- **epoch-v3** (``simbatch/engine_jax.py``, the jitted device
  generator): same ``(time, lane, seq)`` ordering rule as epoch-v2 —
  lane-residue times keep per-seed event times unique, so the heap's
  pop sequence materializes as one argsort and the register/set step
  machines run as a ``jax.lax.scan`` on device. Random blocks come
  from ``jax.random`` (threefry) under a per-seed
  ``PRNGKey(seed mod 2**32)`` with a fixed 12-way subkey split (draw
  order/shapes/dtypes declared in engine_jax.py), so histories differ
  from epoch-v2 draw-by-draw; the MVCC workloads delegate to the
  epoch-v2 per-seed sweep and are bit-identical to it. The 16-seed
  golden-hash pin in tests/test_simbatch_jax.py freezes epoch-v3
  serialization, and the cross-epoch verdict fuzz extends to
  register/set × none/kill/partition against BOTH epoch-v1 and
  epoch-v2.

Runs record their generator epoch (campaign.json ``gen-epoch`` per
row), so stored histories always re-check against the rule that
produced them.

Coroutines are plain ``async def`` functions awaiting our ``Future``s.
"""

from __future__ import annotations

import heapq
import itertools
import random
from collections import deque
from typing import Any, Awaitable, Callable, Coroutine, Generator, Optional

SECOND = 1_000_000_000  # virtual nanoseconds


class Cancelled(BaseException):
    """Raised inside a coroutine when its task is cancelled (op timeout)."""


class Future:
    """A one-shot value container awaitable from coroutines."""

    __slots__ = ("loop", "_state", "_result", "_callbacks")

    PENDING, DONE, ERROR = 0, 1, 2

    def __init__(self, loop: "SimLoop"):
        self.loop = loop
        self._state = Future.PENDING
        self._result: Any = None
        # lazily allocated: most futures (sleeps, queue getters) collect
        # exactly one callback, many collect none before resolution
        self._callbacks: Optional[list] = None

    @property
    def done(self) -> bool:
        return self._state != Future.PENDING

    def set_result(self, value: Any) -> None:
        if self._state:
            return
        self._state = Future.DONE
        self._result = value
        if self._callbacks:
            self._fire()

    def set_exception(self, exc: BaseException) -> None:
        if self._state:
            return
        self._state = Future.ERROR
        self._result = exc
        if self._callbacks:
            self._fire()

    def result(self) -> Any:
        if self._state == Future.DONE:
            return self._result
        if self._state == Future.ERROR:
            raise self._result
        raise RuntimeError("future not done")

    def add_done_callback(self, cb: Callable[["Future"], None]) -> None:
        if self._state:
            self.loop._push_soon(cb, (self,))
        elif self._callbacks is None:
            self._callbacks = [cb]
        else:
            self._callbacks.append(cb)

    def _fire(self) -> None:
        cbs, self._callbacks = self._callbacks, None
        push = self.loop._push_soon
        for cb in cbs:
            push(cb, (self,))

    def __await__(self) -> Generator["Future", None, Any]:
        if not self._state:
            yield self
        return self.result()


class Task(Future):
    """A running coroutine; itself awaitable for the coroutine's result."""

    __slots__ = ("coro", "name", "_waiting_on", "_cancel_requested")

    def __init__(self, loop: "SimLoop", coro: Coroutine, name: str = "task"):
        super().__init__(loop)
        self.coro = coro
        self.name = name
        self._waiting_on: Optional[Future] = None
        self._cancel_requested = False
        loop._push_soon(self._step, (None, None))

    def cancel(self, exc: BaseException | None = None) -> None:
        """Throw Cancelled into the coroutine at its next suspension point."""
        if self._state:
            return
        self._cancel_requested = True
        # Detach from whatever we were awaiting (its wakeup becomes stale)
        # and resume with the cancellation.
        self._waiting_on = None
        self.loop._push_soon(self._step, (None, exc or Cancelled()))

    def _wakeup(self, fut: Future) -> None:
        if self._state or self._waiting_on is not fut:
            return  # stale wakeup (e.g. cancelled meanwhile)
        self._waiting_on = None
        if fut._state == Future.ERROR:
            self._step(None, fut._result)
        else:
            self._step(fut._result, None)

    def _step(self, value: Any, exc: BaseException | None) -> None:
        if self._state:
            return
        if self._cancel_requested and exc is None:
            exc = Cancelled()
        self._cancel_requested = False
        self.loop._current_task = self
        try:
            if exc is not None:
                fut = self.coro.throw(exc)
            else:
                fut = self.coro.send(value)
        except StopIteration as e:
            self.set_result(e.value)
            return
        except Cancelled as e:
            self.set_exception(e)
            return
        except BaseException as e:
            self.set_exception(e)
            return
        finally:
            self.loop._current_task = None
        if not isinstance(fut, Future):
            raise TypeError(f"task {self.name} awaited non-Future {fut!r}")
        self._waiting_on = fut
        fut.add_done_callback(self._wakeup)


class Timer:
    """Handle for a scheduled callback; cancel() makes it a silent no-op.

    Cancellation leaves a tombstone entry in the owning loop's heap; the
    loop counts them and compacts the heap once tombstones dominate (a
    cancel-heavy nemesis schedule would otherwise grow the heap without
    bound, and every push would pay log(dead + live)).
    """

    __slots__ = ("_entry", "_loop")

    def __init__(self, entry: list, loop: "SimLoop"):
        self._entry = entry
        self._loop = loop

    def cancel(self) -> None:
        entry = self._entry
        if entry[2] is not None:
            entry[2] = None
            loop = self._loop
            loop._dead += 1
            if loop._dead > loop.COMPACT_FLOOR and \
                    loop._dead * 2 > len(loop._heap):
                loop._compact()

    @property
    def cancelled(self) -> bool:
        return self._entry[2] is None


class SimLoop:
    """Deterministic discrete-event scheduler with a virtual clock."""

    #: minimum tombstone count before heap compaction kicks in — below
    #: this, a filter + heapify costs more than just popping the dead
    COMPACT_FLOOR = 64

    def __init__(self, seed: int = 0):
        self.now: int = 0  # virtual ns
        self.rng = random.Random(seed)
        self._heap: list[list] = []  # [time, seq, cb_or_None, args]
        self._seq = itertools.count()
        self._current_task: Optional[Task] = None
        self._dead = 0  # cancelled entries still in the heap
        self.tasks: list[Task] = []

    # -- scheduling ---------------------------------------------------------
    def _push_soon(self, cb: Callable, args: tuple) -> None:
        """Hot-path call_soon: no Timer handle, no clamping."""
        heapq.heappush(self._heap, [self.now, next(self._seq), cb, args])

    def _push_at(self, t: int, cb: Callable, args: tuple) -> None:
        """Hot-path call_at: no Timer handle."""
        if t < self.now:
            t = self.now
        heapq.heappush(self._heap, [t, next(self._seq), cb, args])

    def call_at(self, t: int, cb: Callable, *args: Any) -> Timer:
        entry = [max(int(t), self.now), next(self._seq), cb, args]
        heapq.heappush(self._heap, entry)
        return Timer(entry, self)

    def call_later(self, dt: int, cb: Callable, *args: Any) -> Timer:
        return self.call_at(self.now + int(dt), cb, *args)

    def call_soon(self, cb: Callable, *args: Any) -> Timer:
        return self.call_at(self.now, cb, *args)

    def spawn(self, coro: Coroutine, name: str = "task") -> Task:
        t = Task(self, coro, name)
        self.tasks.append(t)
        return t

    # -- primitives ---------------------------------------------------------
    def sleep(self, dt: float) -> Future:
        """Await to pause for dt virtual ns."""
        f = Future(self)
        self._push_at(self.now + int(dt), f.set_result, (None,))
        return f

    def future(self) -> Future:
        return Future(self)

    def _compact(self) -> None:
        """Drop tombstoned entries and restore the heap invariant.

        heapify preserves the total (time, seq) order of live entries, so
        compaction can never reorder callbacks.
        """
        heap = self._heap
        heap[:] = [e for e in heap if e[2] is not None]
        heapq.heapify(heap)
        self._dead = 0

    # -- running ------------------------------------------------------------
    def run(self, until: Optional[Future] = None, max_time: Optional[int] = None) -> Any:
        """Run until `until` completes (or the heap drains)."""
        heap = self._heap
        pop = heapq.heappop
        while heap:
            head = heap[0]
            if head[2] is None:  # cancelled timer: drop silently,
                pop(heap)        # without advancing the clock
                self._dead -= 1
                continue
            t = head[0]
            if until is not None and until._state and t > self.now:
                # Drain same-instant callbacks (e.g. cancellations issued in
                # the completing step) before stopping.
                break
            if max_time is not None and t > max_time:
                self.now = max_time
                break  # event stays queued for a later run()
            self.now = t
            # batch: every entry sharing this timestamp drains in one
            # pass, in (time, seq) pop order — entries a callback pushes
            # at the same instant join the batch, exactly as before
            while heap and heap[0][0] == t:
                entry = pop(heap)
                cb = entry[2]
                if cb is None:
                    self._dead -= 1
                    continue
                cb(*entry[3])
        if until is not None:
            if not until.done:
                raise RuntimeError(
                    f"loop drained at t={self.now} with awaited future pending"
                )
            return until.result()
        return None

    def run_coro(self, coro: Coroutine, name: str = "main") -> Any:
        return self.run(until=self.spawn(coro, name))


# -- structured concurrency helpers (awaitables) ----------------------------

_ACTIVE_LOOP: Optional[SimLoop] = None


def set_current_loop(loop: Optional[SimLoop]) -> None:
    global _ACTIVE_LOOP
    _ACTIVE_LOOP = loop


def current_loop() -> SimLoop:
    if _ACTIVE_LOOP is None:
        raise RuntimeError("no active SimLoop (use set_current_loop)")
    return _ACTIVE_LOOP


async def sleep(dt: float) -> None:
    await current_loop().sleep(dt)


async def wait_for(task: "Task | Future", timeout: float) -> Any:
    """Await a future with a virtual-time timeout.

    On timeout, cancels the task (if cancellable) and raises TimeoutError —
    the analog of the reference's deref-with-timeout (``client.clj:244-252``).
    """
    loop = current_loop()
    gate = Future(loop)

    def on_timeout() -> None:
        if not gate.done:
            gate.set_result("__timeout__")

    timer = loop.call_later(int(timeout), on_timeout)

    def on_done(f: Future) -> None:
        timer.cancel()
        if not gate.done:
            gate.set_result(f)

    task.add_done_callback(on_done)
    first = await gate
    if first == "__timeout__" and not task.done:
        if isinstance(task, Task):
            task.cancel()
        raise TimeoutError(f"timed out after {timeout} ns")
    return task.result()


class Event:
    """Level-triggered event: await until set."""

    def __init__(self, loop: Optional[SimLoop] = None):
        self.loop = loop or current_loop()
        self._set = False
        self._waiters: list[Future] = []

    @property
    def is_set(self) -> bool:
        return self._set

    def set(self) -> None:
        self._set = True
        ws, self._waiters = self._waiters, []
        for w in ws:
            w.set_result(None)

    def clear(self) -> None:
        self._set = False

    async def wait(self) -> None:
        if self._set:
            return
        f = Future(self.loop)
        self._waiters.append(f)
        await f


class Queue:
    """Unbounded FIFO queue."""

    def __init__(self, loop: Optional[SimLoop] = None):
        self.loop = loop or current_loop()
        self._items: deque = deque()
        self._getters: deque[Future] = deque()

    def put(self, item: Any) -> None:
        if self._getters:
            self._getters.popleft().set_result(item)
        else:
            self._items.append(item)

    async def get(self) -> Any:
        if self._items:
            return self._items.popleft()
        f = Future(self.loop)
        self._getters.append(f)
        try:
            return await f
        except BaseException:
            # Cancelled while waiting: withdraw, or re-route an item that
            # was delivered to us but never consumed — to the next waiting
            # getter if any (they won't be woken by a future put), else
            # back to the head of the queue.
            if f in self._getters:
                self._getters.remove(f)
            elif f._state == Future.DONE:
                if self._getters:
                    self._getters.popleft().set_result(f._result)
                else:
                    self._items.appendleft(f._result)
            raise

    def __len__(self) -> int:
        return len(self._items)


async def gather(*aws: Future) -> list:
    """Await all; raises the first child exception after all settle.

    A Cancelled thrown into the *gathering* task itself propagates
    immediately — op-timeout cancellation must terminate the caller.
    """
    results = []
    first_exc: BaseException | None = None
    for a in aws:
        try:
            results.append(await a)
        except Cancelled:
            if a.done and a._state == Future.ERROR:
                # the settled child's own cancellation surfaced via result()
                if first_exc is None:
                    first_exc = a._result
                results.append(None)
            else:
                raise  # thrown into *us* (even if the child happened to
                       # succeed in the same instant)
        except BaseException as e:  # noqa: BLE001 - propagate after settling
            if first_exc is None:
                first_exc = e
            results.append(None)
    if first_exc is not None:
        raise first_exc
    return results
