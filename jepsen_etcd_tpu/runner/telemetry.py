"""Structured run telemetry: spans, counters, and events.

Every phase of a run — setup, generation, history packing, each
checker, each device dispatch — emits into the process-current
``Telemetry`` recorder, which streams one JSON record per line to
``store/<run>/telemetry.jsonl`` and aggregates in memory so the run's
``results.json`` can carry a summary (phase totals, per-checker span
totals, TPU-path counters). The reference treats run artifacts as
first-class evidence (timeline/html at register.clj:112, perf plots,
per-node pcaps); telemetry is the same idea applied to the checker
economics this port exists to measure: a single run's artifacts explain
its own checker cost the way PERF.md's bench cells do.

Record schema (one JSON object per line; ``SPAN_FIELDS`` /
``COUNTER_FIELDS`` / ``EVENT_FIELDS`` pin the field sets — bench.py
emits the same schema per cell so BENCH rounds and live runs are
comparable with one reader):

    {"kind": "span",    "name": ..., "t0": ..., "t1": ...,
     "dur_s": ..., "attrs": {...}}
    {"kind": "counter", "name": ..., "value": ...}
    {"kind": "event",   "name": ..., "t": ..., "attrs": {...}}

Span-name conventions: ``phase:<name>`` for run phases (setup,
generate, stream-finalize, teardown, check, save), ``checker:<name>``
for one composed checker's pass, everything else dotted by subsystem
(``wgl.check_packed``, ``mxu.launch``, ``closure.device``; streamed
runs add per-chunk ``stream.chunk`` dispatch spans, ``stream.finalize``
consumer spans, and the ``stream.{chunks,flushed_events,resume_rungs,
backlog_peak,pack_reuse,*_reuse}`` counters from runner/stream.py).
Times are ``time.monotonic()`` wall seconds — telemetry measures
host/device cost, not virtual time.

Deep code (ops/, checkers/) reaches the recorder through ``current()``,
which returns a no-op ``NullTelemetry`` outside a run, so kernels and
packers are instrumentable without threading a handle through every
call — and pay only an attribute lookup when telemetry is off.
"""

from __future__ import annotations

import json
import math
import socket
import threading
import time
from typing import Any, Iterable, Optional

SCHEMA_VERSION = 1

#: the pinned field sets — a record of each kind carries exactly these
#: (traced recorders append ``trace`` and, when set, ``parent`` AFTER
#: the pinned fields; traceless records carry exactly the tuple)
SPAN_FIELDS = ("kind", "name", "t0", "t1", "dur_s", "attrs")
COUNTER_FIELDS = ("kind", "name", "value")
EVENT_FIELDS = ("kind", "name", "t", "attrs")
HIST_FIELDS = ("kind", "name", "count", "sum", "min", "max", "buckets")

#: total record cap per run: past it records are counted as dropped,
#: never buffered (a pathological dispatch loop must not eat the disk)
MAX_RECORDS = 200_000

#: the canonical name inventory. The cross-run dashboard
#: (forensics/telemetry readers) joins series by name, so a typo'd
#: name silently starts a fresh series; graftlint TEL002 checks every
#: ``span``/``counter``/``event`` call site against this literal (read
#: via ast.literal_eval — never imported). ``*`` matches a
#: parameterized segment (``phase:<name>``, ``stream.<field>_reuse``).
#: Adding an emit site means adding its name here, in the same commit.
REGISTRY = {
    "spans": (
        "phase:*",            # setup/generate/teardown/check/save/...
        "checker:*",          # one composed checker's pass
        "cell:*",             # bench.py per-cell spans
        "wgl.spill",
        "wgl.batch-dispatch",
        "wgl.check_packed",
        "wgl.pack",
        "wgl.pack-batch",
        "mxu.dispatch",
        "mxu.launch",
        "mxu.collect",
        "closure.device",
        "closure.host",
        "stream.chunk",
        "stream.finalize",
        "campaign.sweep",     # runner/campaign.py: the whole pool pass
        "service.tick",       # runner/checker_service.py: one coalesced
                              # device dispatch window
        "fused.gen",          # runner/stream.py FusedPipeline: the
                              # producer's generation leg
        "fused.check",        # ... and the consumer's check leg
    ),
    "hists": (
        "op.latency.*",       # per-op-class completion latency, seconds
                              # (checkers/perf.py; virtual time in sim)
        "wgl.check_packed",   # auto-hist of the span walls
        "stream.chunk",       # auto-hist of chunk dispatch walls
        "service.tick",       # auto-hist of service dispatch windows
        "service.queue_wait_s",   # producer-side: this run's packs'
                                  # submit->dispatch waits as reported
                                  # in the service reply
        "stream.chunk_lag_s",  # enqueue->consume delay per chunk,
                               # runner/stream.py
        "wgl.rung_waves",      # one sample per ladder-rung attempt,
                               # value = rung frontier budget — log2
                               # buckets put each rung in its own
                               # bucket, so counts read as search-depth
                               # shape (ops/wgl.py; the guided coverage
                               # vector's wave-histogram feature)
    ),
    "counters": (
        "generate.ops_per_s",
        "columns.events",
        "columns.keyed",
        "columns.extras",
        "columns.disabled",
        "stream.chunks",
        "stream.flushed_events",
        "stream.backlog_peak",
        "stream.resume_rungs",
        "stream.pack_reuse",
        "stream.*_reuse",     # per-consumer reuse, runner/stream.py
        "engine.*",           # verdict-engine routing tally,
                              # checkers/tpu_linearizable.py
        "wgl.dispatches",
        "wgl.rungs",
        "wgl.max-frontier",
        "wgl.host-spill",
        "wgl.waves",              # deepest wave loop of any dispatch
                                  # (mode=max) — the coverage envelope's
                                  # wave-depth dimension
        "mxu.dispatches",
        "campaign.runs",          # runner/campaign.py sweep accounting
        "campaign.completed",
        "campaign.failed",
        "campaign.skipped",
        "campaign.errors",
        "campaign.hosts",         # runner/host_agent.py fan-out:
                                  # worker agents registered at sweep
                                  # start
        "campaign.agent_requeues",  # specs re-queued after an agent
                                  # died mid-run (requeue-capped; past
                                  # the cap the driver runs inline)
        "service.requests",       # runner/checker_service.py batching:
        "service.submitted",      # packs received across all runners
        "service.coalesced",      # packs beyond the first per group
        "service.ticks",          # dispatch windows run
        "service.group_ticks",    # sum of (bucket, width) groups/tick
                                  # == the dispatch budget the coalescer
                                  # is held to (~1 dispatch per group)
        "service.batch_occupancy",  # max packs in one tick (mode=max)
        "service.queue_wait_s",   # total submit->dispatch wait
        "service.device_busy_s.*",  # per-device busy wall attributed
                                  # by dispatch (dev = platform+id; one
                                  # series per chip — fan-counted for a
                                  # sharded tick, every lane chip burns
                                  # the job's wall)
        "service.device_dispatches.*",  # group dispatches per chip
                                  # (fan-counted); ledger identity:
                                  # Σ over chips == service.group_ticks
                                  # + service.shard_fanout
        "service.device_occupancy",  # max distinct chips busy in one
                                  # tick (mode=max)
        "service.sharded_ticks",  # ticks whose single group spread its
                                  # batch axis over the whole mesh
                                  # (shard_map when oversized, GSPMD
                                  # scatter otherwise)
        "service.shard_fanout",   # extra lane-dispatches sharded ticks
                                  # added beyond one-per-group (Σ of
                                  # lanes-1), balancing the per-device
                                  # dispatch ledger
        "service.pack_s",         # host packing wall per tick (the
                                  # half double-buffering overlaps with
                                  # the previous tick's device wall)
        "service.fallback",       # runner-side degradations to
                                  # in-process checking
        "service.fallback.*",     # fallback groups placed per chip by
                                  # fallback_device_for (the service's
                                  # sticky map honored in-process)
        "service.checks",         # runner-side: service round-trips
                                  # that returned verdicts
        "service.shipped",        # runner-side packs shipped; summed
                                  # over a campaign's runs this equals
                                  # the service's service.submitted
        "service.host_submitted.*",  # packs received per generator
                                  # host (JET-HOST preamble); ledger:
                                  # Σ over hosts' rows'
                                  # service_shipped == this series —
                                  # the cross-host shipped==submitted
                                  # join
        "service.admission_rejects",  # check requests bounced BUSY at
                                  # the door (queue/in-flight caps) —
                                  # counted BEFORE deserialization
        "service.busy_retries",   # client-side: BUSY replies absorbed
                                  # by backoff-and-retry
        "service.auth_rejects",   # hello frames with a wrong/missing
                                  # shared-secret token
        "service.reconnects",     # client-side: successful reconnects
                                  # after >=1 failure (the broken
                                  # latch healing)
        "service.heartbeats_sent",  # service-side liveness frames to
                                  # connections with in-flight work
        "service.heartbeats_seen",  # client-side heartbeats consumed
                                  # while waiting (distinguishes slow
                                  # from dead)
        "service.bad_requests",   # undeserializable/oversized check
                                  # bodies answered with a structured
                                  # error (connection survives)
        "service.shutdown_leaked_threads",  # threads still alive
                                  # after close() joins timed out
        "independent.keys",       # per-key fanout of the independent
                                  # split (the producer side of the
                                  # batching axis)
        "net.links",              # net/plane.py proxy fleet: proxies
                                  # raised in front of node ports
        "net.dropped_conns",      # connections blackholed by a drop
                                  # rule or refused (node down)
        "net.dropped_chunks",     # chunks discarded by a lossy-link
                                  # drop_prob rule (netem-loss analog)
        "net.delayed_bytes",      # bytes that paid injected latency
        "net.active_rules",       # peak concurrent fault rules
                                  # (mode=max)
        "net.accept_errors",      # transient accept() failures the
                                  # proxy survived (EMFILE, ...)
        "genbatch.cells",         # simbatch batched generation (campaign
                                  # epoch-v2 routing + bench batched
                                  # leg): (workload, nemesis) cells run
        "genbatch.seeds",         # seeds generated across all cells
        "genbatch.steps",         # lockstep columnar steps executed
        "genbatch.events",        # history rows born as columns
        "genbatch.ops_per_s",     # aggregate events per generation wall
                                  # second across the batch (mode=max)
        "genbatch.compactions",   # BatchHeap tombstone compactions
        "fused.seeds",            # runner/stream.py FusedPipeline:
                                  # seeds generated+checked through the
                                  # overlapped gen->check pipeline
        "fused.packs",            # per-key packs checked by the
                                  # pipeline's consumer leg
        "fused.waves",            # total check_prefix waves the
                                  # consumer advanced while the
                                  # producer was still generating
        "live.records",           # campaign LiveCollector: records
                                  # received over the live socket
        "live.dropped",           # records shed by the bounded queue
                                  # (backpressure, never blocking)
        "guided.generations",     # runner/guided.py search accounting:
                                  # run_campaign waves driven
        "guided.runs",            # runs scored by the scheduler
        "guided.errors",          # rows without a checker verdict
                                  # (never scored — harness noise)
        "guided.failures",        # rows with a real failing verdict
        "guided.novelty",         # summed novelty score admitted to
                                  # the corpus
        "guided.signatures",      # distinct verdict signatures seen
        "guided.corpus",          # peak corpus size (mode=max)
        "guided.mutations",       # mutants generated
        "guided.crossovers",      # crossover children generated
        "guided.corpus-imported",  # ancestors merged from --corpus-in
        "guided.corpus_retired",  # imported ancestors evicted after a
                                  # full generation below score 1
        "store.index_rows",       # runner/store_index.py: rows written
                                  # by a full `store index --rebuild`
        "store.index_writes",     # incremental index rows written at
                                  # save_run / campaign-fold time
        "store.compacted",        # passing runs demoted to index rows
                                  # + summaries by `store compact`
        "store.compact_skipped_failures",  # compaction candidates left
                                  # untouched because they failed
        "shrink.runs",            # runner/shrink.py: shrinks attempted
        "shrink.candidates",      # candidate schedules re-executed
        "shrink.rounds",          # ddmin rounds run
        "shrink.accepted",        # shrinks that reduced the schedule
        "shrink.irreproducible",  # failures that did not reproduce
                                  # under re-execution (left unshrunk)
        "shrink.artifacts",       # shrink.json artifacts written
        "mvcc.reads",             # checkers/mvcc.py consistency
        "mvcc.keys",              # surfaces: observations consumed per
        "mvcc.writes",            # check over the core/mvcc.py model
        "mvcc.ranges",
        "mvcc.grants",
        "mvcc.watches",
        "mvcc.watch-events",
        "mvcc.compactions",
        "mvcc.violations",        # violations across all four surface
                                  # checkers (0 on a clean run)
    ),
    "events": (
        "telemetry.dropped",
        "campaign.run",           # one completed campaign run (attrs:
                                  # workload, nemesis, seed, valid)
        "guided.generation",      # one guided generation dispatched
                                  # (attrs: gen, size)
    ),
}


#: histogram geometry: 64 log2 buckets starting at 1 microsecond.
#: Bucket 0 is [0, HIST_MIN] (plus any negative clock skew); bucket i
#: covers (HIST_MIN * 2**(i-1), HIST_MIN * 2**i]. 64 doublings from
#: 1 us tops out near 9e12 s — every latency this harness can see fits.
HIST_MIN = 1e-6
HIST_BUCKETS = 64

#: spans whose wall durations are ALSO folded into a same-named
#: histogram on close of each span (the hot paths ISSUE 14 names)
HIST_SPAN_NAMES = frozenset(
    {"wgl.check_packed", "stream.chunk", "service.tick"})


class Hist:
    """Fixed-geometry log2 histogram: bounded memory (64 ints), exact
    count/sum/min/max, mergeable across runs by bucket-wise addition —
    the HDR-histogram idea reduced to the precision dashboards need.
    """

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self) -> None:
        self.counts = [0] * HIST_BUCKETS
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    @staticmethod
    def bucket_of(value: float) -> int:
        if not value > HIST_MIN:
            return 0
        # bucket i covers (MIN*2**(i-1), MIN*2**i]: upper edge inclusive
        return max(1, min(HIST_BUCKETS - 1,
                          int(math.ceil(math.log2(value / HIST_MIN)))))

    @staticmethod
    def bucket_edges(i: int) -> tuple:
        """(lo, hi) of bucket i; bucket 0 starts at 0."""
        if i <= 0:
            return (0.0, HIST_MIN)
        return (HIST_MIN * 2.0 ** (i - 1), HIST_MIN * 2.0 ** i)

    def record(self, value: float) -> None:
        self.counts[self.bucket_of(value)] += 1
        self.count += 1
        self.sum += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    def record_many(self, values: Iterable[float]) -> None:
        """Vectorized bulk insert (used for per-class op latencies,
        tens of thousands of points per run)."""
        import numpy as np
        a = np.asarray(values if hasattr(values, "__len__")
                       else list(values), dtype=np.float64).ravel()
        a = a[np.isfinite(a)]
        if a.size == 0:
            return
        idx = np.zeros(a.shape, dtype=np.int64)
        big = a > HIST_MIN
        if big.any():
            idx[big] = np.clip(
                np.ceil(np.log2(a[big] / HIST_MIN)).astype(np.int64),
                1, HIST_BUCKETS - 1)
        for i, c in zip(*np.unique(idx, return_counts=True)):
            self.counts[int(i)] += int(c)
        self.count += int(a.size)
        self.sum += float(a.sum())
        self.min = min(self.min, float(a.min()))
        self.max = max(self.max, float(a.max()))

    def merge(self, other: "Hist") -> "Hist":
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def percentile(self, q: float) -> Optional[float]:
        """q in [0, 100]; linear interpolation inside the landing
        bucket, clamped to the exact observed [min, max]."""
        if not self.count:
            return None
        target = (q / 100.0) * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if not c:
                continue
            if cum + c >= target:
                lo, hi = self.bucket_edges(i)
                v = lo + ((target - cum) / c) * (hi - lo)
                return min(max(v, self.min), self.max)
            cum += c
        return self.max

    # -- serialization --------------------------------------------------------
    def to_dict(self) -> dict:
        """Sparse, mergeable, JSON-stable form used in summaries,
        campaign rows, and ``"hist"`` records."""
        if not self.count:
            return {"count": 0, "sum": 0.0, "min": None, "max": None,
                    "p50": None, "p95": None, "p99": None,
                    "buckets": {}}
        return {"count": self.count, "sum": round(self.sum, 9),
                "min": round(self.min, 9), "max": round(self.max, 9),
                "p50": round(self.percentile(50), 9),
                "p95": round(self.percentile(95), 9),
                "p99": round(self.percentile(99), 9),
                "buckets": {str(i): c for i, c in enumerate(self.counts)
                            if c}}

    @classmethod
    def from_dict(cls, d: dict) -> "Hist":
        h = cls()
        for k, c in (d.get("buckets") or {}).items():
            h.counts[int(k)] += int(c)
        h.count = int(d.get("count") or 0)
        h.sum = float(d.get("sum") or 0.0)
        if d.get("min") is not None:
            h.min = float(d["min"])
        if d.get("max") is not None:
            h.max = float(d["max"])
        return h


class _Span:
    """Context manager for one span; ``set(**attrs)`` attaches result
    attributes (engine, rung count, ...) before the span closes."""

    __slots__ = ("_tel", "name", "attrs", "t0")

    def __init__(self, tel: "Telemetry", name: str, attrs: dict):
        self._tel = tel
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0

    def set(self, **attrs: Any) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "_Span":
        self.t0 = self._tel._clock()
        return self

    def __exit__(self, *exc) -> None:
        self._tel._end_span(self)


class _NullSpan:
    """No-op span: zero work outside a run."""

    __slots__ = ()

    def set(self, **attrs: Any) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


_NULL_SPAN = _NullSpan()


class NullTelemetry:
    """The recorder used outside a run: every call is a no-op."""

    enabled = False
    trace = None

    def span(self, name: str, **attrs: Any) -> _NullSpan:
        return _NULL_SPAN

    def counter(self, name: str, value: float = 1,
                mode: str = "sum") -> None:
        pass

    def event(self, name: str, **attrs: Any) -> None:
        pass

    def hist(self, name: str, value: float) -> None:
        pass

    def hist_many(self, name: str, values: Iterable[float]) -> None:
        pass

    def summary(self) -> dict:
        return {}

    def close(self) -> None:
        pass


NULL = NullTelemetry()


class Telemetry:
    """Span/counter recorder streaming to a .jsonl file.

    Thread-safe: live runs complete ops from socket threads, and a
    counter bump must never corrupt the stream. The file opens lazily
    on the first record and every record is written (buffered by the
    underlying file object) as it happens — a crashed run keeps the
    spans it completed.
    """

    enabled = True

    def __init__(self, path: Optional[str] = None,
                 clock=time.monotonic,
                 max_records: int = MAX_RECORDS,
                 trace: Optional[str] = None,
                 parent: Optional[str] = None,
                 sink: Optional[str] = None):
        self.path = path
        self._clock = clock
        self._fh = None
        self._lock = threading.Lock()
        self._max_records = max_records
        self.records = 0
        self.dropped = 0
        #: trace identity stamped on every record (``trace``/``parent``
        #: fields AFTER the pinned tuple; absent when trace is None)
        self.trace = trace
        self.parent = parent
        # name -> [count, total_s]; insertion-ordered like the file
        self._span_agg: dict[str, list] = {}
        # name -> value; mode "max" counters keep the running max
        self._counters: dict[str, float] = {}
        # name -> Hist; flushed as "hist" records at close
        self._hists: dict[str, Hist] = {}
        self._closed = False
        # optional live sink: an AF_UNIX datagram socket path the
        # campaign collector listens on; strictly best-effort — a full
        # or missing socket drops the datagram, never blocks the run
        self._sink_path = sink
        self._sink_sock = None
        self._sink_errors = 0
        self.sink_dropped = 0

    # -- recording -----------------------------------------------------------
    def span(self, name: str, **attrs: Any) -> _Span:
        return _Span(self, name, attrs)

    def _end_span(self, sp: _Span) -> None:
        t1 = self._clock()
        dur = t1 - sp.t0
        with self._lock:
            agg = self._span_agg.setdefault(sp.name, [0, 0.0])
            agg[0] += 1
            agg[1] += dur
            if sp.name in HIST_SPAN_NAMES:
                self._hists.setdefault(sp.name, Hist()).record(dur)
            self._write({"kind": "span", "name": sp.name,
                         "t0": sp.t0, "t1": t1, "dur_s": dur,
                         "attrs": sp.attrs})

    def counter(self, name: str, value: float = 1,
                mode: str = "sum") -> None:
        """Accumulate a named counter; ``mode="max"`` keeps the running
        maximum (e.g. peak frontier width) instead of the sum. Counters
        are flushed as records at close, not per bump."""
        with self._lock:
            if mode == "max":
                self._counters[name] = max(
                    self._counters.get(name, value), value)
            else:
                self._counters[name] = self._counters.get(name, 0) + value

    def event(self, name: str, **attrs: Any) -> None:
        with self._lock:
            self._write({"kind": "event", "name": name,
                         "t": self._clock(), "attrs": attrs})

    def hist(self, name: str, value: float) -> None:
        """Fold one observation into the named histogram. Histograms
        live in memory (64 ints each) and flush as one ``"hist"``
        record at close."""
        with self._lock:
            self._hists.setdefault(name, Hist()).record(value)

    def hist_many(self, name: str, values: Iterable[float]) -> None:
        """Vectorized :meth:`hist` for bulk observations."""
        with self._lock:
            self._hists.setdefault(name, Hist()).record_many(values)

    def _write(self, rec: dict) -> None:
        # caller holds the lock
        if self._closed:
            return
        if self.records >= self._max_records:
            self.dropped += 1
            return
        self.records += 1
        self._emit(rec)

    def _emit(self, rec: dict) -> None:
        """Serialize once, append to the file and forward to the live
        sink (both best-effort independent). Caller holds the lock and
        has already done cap accounting."""
        if self.path is None and self._sink_path is None:
            return
        if self.trace is not None:
            rec["trace"] = self.trace
            if self.parent is not None:
                rec["parent"] = self.parent
        line = json.dumps(rec, default=repr)
        if self.path is not None:
            if self._fh is None:
                self._fh = open(self.path, "w")
            self._fh.write(line + "\n")
        if self._sink_path is not None:
            self._sink_send(line.encode("utf-8", "replace"))

    def _sink_send(self, data: bytes) -> None:
        # caller holds the lock; drop-and-count, never block or raise
        if self._sink_sock is None:
            try:
                self._sink_sock = socket.socket(
                    socket.AF_UNIX, socket.SOCK_DGRAM)
                self._sink_sock.setblocking(False)
            except OSError:
                self._sink_path = None
                return
        try:
            self._sink_sock.sendto(data, self._sink_path)
            self._sink_errors = 0
        except (BlockingIOError, InterruptedError):
            self.sink_dropped += 1     # receiver backlogged: shed
        except OSError:
            self.sink_dropped += 1
            self._sink_errors += 1
            if self._sink_errors >= 8:  # collector gone: stop trying
                self._sink_path = None

    # -- reading -------------------------------------------------------------
    def summary(self) -> dict:
        """Aggregate view for results.json: per-span-name totals (the
        file's span records sum to exactly these — same floats, same
        order), counters, and the phase / per-checker convenience maps
        derived from the span-name conventions."""
        with self._lock:
            spans = {name: {"count": c, "total_s": t}
                     for name, (c, t) in self._span_agg.items()}
            counters = dict(self._counters)
            hists = {name: h.to_dict()
                     for name, h in self._hists.items()}
            dropped = self.dropped
        out = {
            "schema": SCHEMA_VERSION,
            "spans": spans,
            "counters": counters,
            "phases": {n[len("phase:"):]: v["total_s"]
                       for n, v in spans.items()
                       if n.startswith("phase:")},
            "checkers": {n[len("checker:"):]: v["total_s"]
                         for n, v in spans.items()
                         if n.startswith("checker:")},
        }
        if hists:
            out["hists"] = hists
        if self.trace is not None:
            out["trace"] = self.trace
        if dropped:
            out["dropped"] = dropped
        if self.path is not None:
            import os
            out["file"] = os.path.basename(self.path)
        return out

    def close(self) -> None:
        """Flush counters and histograms as records and close the
        stream. Idempotent."""
        with self._lock:
            if self._closed:
                return
            for name, value in self._counters.items():
                if self.records < self._max_records:
                    self.records += 1
                    self._emit({"kind": "counter", "name": name,
                                "value": value})
                else:
                    self.dropped += 1
            for name, h in self._hists.items():
                if self.records < self._max_records:
                    self.records += 1
                    d = h.to_dict()
                    self._emit({"kind": "hist", "name": name,
                                "count": d["count"], "sum": d["sum"],
                                "min": d["min"], "max": d["max"],
                                "buckets": d["buckets"]})
                else:
                    self.dropped += 1
            if self.dropped:
                self._emit({"kind": "event",
                            "name": "telemetry.dropped",
                            "t": self._clock(),
                            "attrs": {"dropped": self.dropped}})
            self._closed = True
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            if self._sink_sock is not None:
                try:
                    self._sink_sock.close()
                except OSError:
                    pass
                self._sink_sock = None


def load_jsonl(path: str) -> tuple:
    """Read a ``*.jsonl`` artifact tolerantly: ``(records, skipped)``.

    A killed run (or a reader racing the writer) leaves a truncated
    trailing line; readers must skip-and-count, never crash. Non-dict
    rows and undecodable bytes count as skipped too."""
    records: list = []
    skipped = 0
    try:
        fh = open(path, "rb")
    except OSError:
        return records, skipped
    with fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line.decode("utf-8", "replace"))
            except ValueError:
                skipped += 1
                continue
            if not isinstance(rec, dict):
                skipped += 1
                continue
            records.append(rec)
    return records, skipped


#: the process-current recorder; NULL outside a run
_current: Any = NULL

#: per-thread override: a service dispatcher (or any worker thread
#: that must record into its own stream) pins its recorder here
#: WITHOUT touching the process-global — concurrent threads keep
#: recording into theirs, closing the swap race checker_service.py
#: used to have
_tls = threading.local()


def current() -> Any:
    """The calling thread's pinned Telemetry if one is set (see
    :func:`set_thread_current`), else the process-current recorder,
    else the no-op NULL outside a run."""
    tel = getattr(_tls, "tel", None)
    return tel if tel is not None else _current


def set_current(tel: Optional[Telemetry]) -> None:
    """Install (or with None, clear) the process-current recorder."""
    global _current
    _current = tel if tel is not None else NULL


def set_thread_current(tel: Optional[Telemetry]) -> None:
    """Pin (or with None, unpin) a recorder for THIS thread only.
    ``current()`` prefers the thread pin over the process-global, so a
    long-lived worker thread can record into its own stream while
    other threads' runs stay untouched."""
    _tls.tel = tel
