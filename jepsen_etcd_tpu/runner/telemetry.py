"""Structured run telemetry: spans, counters, and events.

Every phase of a run — setup, generation, history packing, each
checker, each device dispatch — emits into the process-current
``Telemetry`` recorder, which streams one JSON record per line to
``store/<run>/telemetry.jsonl`` and aggregates in memory so the run's
``results.json`` can carry a summary (phase totals, per-checker span
totals, TPU-path counters). The reference treats run artifacts as
first-class evidence (timeline/html at register.clj:112, perf plots,
per-node pcaps); telemetry is the same idea applied to the checker
economics this port exists to measure: a single run's artifacts explain
its own checker cost the way PERF.md's bench cells do.

Record schema (one JSON object per line; ``SPAN_FIELDS`` /
``COUNTER_FIELDS`` / ``EVENT_FIELDS`` pin the field sets — bench.py
emits the same schema per cell so BENCH rounds and live runs are
comparable with one reader):

    {"kind": "span",    "name": ..., "t0": ..., "t1": ...,
     "dur_s": ..., "attrs": {...}}
    {"kind": "counter", "name": ..., "value": ...}
    {"kind": "event",   "name": ..., "t": ..., "attrs": {...}}

Span-name conventions: ``phase:<name>`` for run phases (setup,
generate, stream-finalize, teardown, check, save), ``checker:<name>``
for one composed checker's pass, everything else dotted by subsystem
(``wgl.check_packed``, ``mxu.launch``, ``closure.device``; streamed
runs add per-chunk ``stream.chunk`` dispatch spans, ``stream.finalize``
consumer spans, and the ``stream.{chunks,flushed_events,resume_rungs,
backlog_peak,pack_reuse,*_reuse}`` counters from runner/stream.py).
Times are ``time.monotonic()`` wall seconds — telemetry measures
host/device cost, not virtual time.

Deep code (ops/, checkers/) reaches the recorder through ``current()``,
which returns a no-op ``NullTelemetry`` outside a run, so kernels and
packers are instrumentable without threading a handle through every
call — and pay only an attribute lookup when telemetry is off.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Optional

SCHEMA_VERSION = 1

#: the pinned field sets — a record of each kind carries exactly these
SPAN_FIELDS = ("kind", "name", "t0", "t1", "dur_s", "attrs")
COUNTER_FIELDS = ("kind", "name", "value")
EVENT_FIELDS = ("kind", "name", "t", "attrs")

#: total record cap per run: past it records are counted as dropped,
#: never buffered (a pathological dispatch loop must not eat the disk)
MAX_RECORDS = 200_000

#: the canonical name inventory. The cross-run dashboard
#: (forensics/telemetry readers) joins series by name, so a typo'd
#: name silently starts a fresh series; graftlint TEL002 checks every
#: ``span``/``counter``/``event`` call site against this literal (read
#: via ast.literal_eval — never imported). ``*`` matches a
#: parameterized segment (``phase:<name>``, ``stream.<field>_reuse``).
#: Adding an emit site means adding its name here, in the same commit.
REGISTRY = {
    "spans": (
        "phase:*",            # setup/generate/teardown/check/save/...
        "checker:*",          # one composed checker's pass
        "cell:*",             # bench.py per-cell spans
        "wgl.spill",
        "wgl.batch-dispatch",
        "wgl.check_packed",
        "wgl.pack",
        "wgl.pack-batch",
        "mxu.dispatch",
        "mxu.launch",
        "mxu.collect",
        "closure.device",
        "closure.host",
        "stream.chunk",
        "stream.finalize",
        "campaign.sweep",     # runner/campaign.py: the whole pool pass
        "service.tick",       # runner/checker_service.py: one coalesced
                              # device dispatch window
    ),
    "counters": (
        "generate.ops_per_s",
        "columns.events",
        "columns.keyed",
        "columns.extras",
        "columns.disabled",
        "stream.chunks",
        "stream.flushed_events",
        "stream.backlog_peak",
        "stream.resume_rungs",
        "stream.pack_reuse",
        "stream.*_reuse",     # per-consumer reuse, runner/stream.py
        "engine.*",           # verdict-engine routing tally,
                              # checkers/tpu_linearizable.py
        "wgl.dispatches",
        "wgl.rungs",
        "wgl.max-frontier",
        "wgl.host-spill",
        "mxu.dispatches",
        "campaign.runs",          # runner/campaign.py sweep accounting
        "campaign.completed",
        "campaign.failed",
        "campaign.skipped",
        "campaign.errors",
        "service.requests",       # runner/checker_service.py batching:
        "service.submitted",      # packs received across all runners
        "service.coalesced",      # packs beyond the first per group
        "service.ticks",          # dispatch windows run
        "service.group_ticks",    # sum of (bucket, width) groups/tick
                                  # == the dispatch budget the coalescer
                                  # is held to (~1 dispatch per group)
        "service.batch_occupancy",  # max packs in one tick (mode=max)
        "service.queue_wait_s",   # total submit->dispatch wait
        "service.fallback",       # runner-side degradations to
                                  # in-process checking
        "service.checks",         # runner-side: service round-trips
                                  # that returned verdicts
        "service.shipped",        # runner-side packs shipped; summed
                                  # over a campaign's runs this equals
                                  # the service's service.submitted
        "independent.keys",       # per-key fanout of the independent
                                  # split (the producer side of the
                                  # batching axis)
        "net.links",              # net/plane.py proxy fleet: proxies
                                  # raised in front of node ports
        "net.dropped_conns",      # connections blackholed by a drop
                                  # rule or refused (node down)
        "net.dropped_chunks",     # chunks discarded by a lossy-link
                                  # drop_prob rule (netem-loss analog)
        "net.delayed_bytes",      # bytes that paid injected latency
        "net.active_rules",       # peak concurrent fault rules
                                  # (mode=max)
        "net.accept_errors",      # transient accept() failures the
                                  # proxy survived (EMFILE, ...)
        "genbatch.cells",         # simbatch batched generation (campaign
                                  # epoch-v2 routing + bench batched
                                  # leg): (workload, nemesis) cells run
        "genbatch.seeds",         # seeds generated across all cells
        "genbatch.steps",         # lockstep columnar steps executed
        "genbatch.events",        # history rows born as columns
        "genbatch.ops_per_s",     # aggregate events per generation wall
                                  # second across the batch (mode=max)
        "genbatch.compactions",   # BatchHeap tombstone compactions
    ),
    "events": (
        "telemetry.dropped",
        "campaign.run",           # one completed campaign run (attrs:
                                  # workload, nemesis, seed, valid)
    ),
}


class _Span:
    """Context manager for one span; ``set(**attrs)`` attaches result
    attributes (engine, rung count, ...) before the span closes."""

    __slots__ = ("_tel", "name", "attrs", "t0")

    def __init__(self, tel: "Telemetry", name: str, attrs: dict):
        self._tel = tel
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0

    def set(self, **attrs: Any) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "_Span":
        self.t0 = self._tel._clock()
        return self

    def __exit__(self, *exc) -> None:
        self._tel._end_span(self)


class _NullSpan:
    """No-op span: zero work outside a run."""

    __slots__ = ()

    def set(self, **attrs: Any) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


_NULL_SPAN = _NullSpan()


class NullTelemetry:
    """The recorder used outside a run: every call is a no-op."""

    enabled = False

    def span(self, name: str, **attrs: Any) -> _NullSpan:
        return _NULL_SPAN

    def counter(self, name: str, value: float = 1,
                mode: str = "sum") -> None:
        pass

    def event(self, name: str, **attrs: Any) -> None:
        pass

    def summary(self) -> dict:
        return {}

    def close(self) -> None:
        pass


NULL = NullTelemetry()


class Telemetry:
    """Span/counter recorder streaming to a .jsonl file.

    Thread-safe: live runs complete ops from socket threads, and a
    counter bump must never corrupt the stream. The file opens lazily
    on the first record and every record is written (buffered by the
    underlying file object) as it happens — a crashed run keeps the
    spans it completed.
    """

    enabled = True

    def __init__(self, path: Optional[str] = None,
                 clock=time.monotonic,
                 max_records: int = MAX_RECORDS):
        self.path = path
        self._clock = clock
        self._fh = None
        self._lock = threading.Lock()
        self._max_records = max_records
        self.records = 0
        self.dropped = 0
        # name -> [count, total_s]; insertion-ordered like the file
        self._span_agg: dict[str, list] = {}
        # name -> value; mode "max" counters keep the running max
        self._counters: dict[str, float] = {}
        self._closed = False

    # -- recording -----------------------------------------------------------
    def span(self, name: str, **attrs: Any) -> _Span:
        return _Span(self, name, attrs)

    def _end_span(self, sp: _Span) -> None:
        t1 = self._clock()
        dur = t1 - sp.t0
        with self._lock:
            agg = self._span_agg.setdefault(sp.name, [0, 0.0])
            agg[0] += 1
            agg[1] += dur
            self._write({"kind": "span", "name": sp.name,
                         "t0": sp.t0, "t1": t1, "dur_s": dur,
                         "attrs": sp.attrs})

    def counter(self, name: str, value: float = 1,
                mode: str = "sum") -> None:
        """Accumulate a named counter; ``mode="max"`` keeps the running
        maximum (e.g. peak frontier width) instead of the sum. Counters
        are flushed as records at close, not per bump."""
        with self._lock:
            if mode == "max":
                self._counters[name] = max(
                    self._counters.get(name, value), value)
            else:
                self._counters[name] = self._counters.get(name, 0) + value

    def event(self, name: str, **attrs: Any) -> None:
        with self._lock:
            self._write({"kind": "event", "name": name,
                         "t": self._clock(), "attrs": attrs})

    def _write(self, rec: dict) -> None:
        # caller holds the lock
        if self._closed:
            return
        if self.records >= self._max_records:
            self.dropped += 1
            return
        self.records += 1
        if self.path is None:
            return
        if self._fh is None:
            self._fh = open(self.path, "w")
        self._fh.write(json.dumps(rec, default=repr) + "\n")

    # -- reading -------------------------------------------------------------
    def summary(self) -> dict:
        """Aggregate view for results.json: per-span-name totals (the
        file's span records sum to exactly these — same floats, same
        order), counters, and the phase / per-checker convenience maps
        derived from the span-name conventions."""
        with self._lock:
            spans = {name: {"count": c, "total_s": t}
                     for name, (c, t) in self._span_agg.items()}
            counters = dict(self._counters)
            dropped = self.dropped
        out = {
            "schema": SCHEMA_VERSION,
            "spans": spans,
            "counters": counters,
            "phases": {n[len("phase:"):]: v["total_s"]
                       for n, v in spans.items()
                       if n.startswith("phase:")},
            "checkers": {n[len("checker:"):]: v["total_s"]
                         for n, v in spans.items()
                         if n.startswith("checker:")},
        }
        if dropped:
            out["dropped"] = dropped
        if self.path is not None:
            import os
            out["file"] = os.path.basename(self.path)
        return out

    def close(self) -> None:
        """Flush counters as records and close the stream. Idempotent."""
        with self._lock:
            if self._closed:
                return
            for name, value in self._counters.items():
                if self.records < self._max_records:
                    self.records += 1
                    if self.path is not None:
                        if self._fh is None:
                            self._fh = open(self.path, "w")
                        self._fh.write(json.dumps(
                            {"kind": "counter", "name": name,
                             "value": value}) + "\n")
                else:
                    self.dropped += 1
            if self.dropped and self._fh is not None:
                self._fh.write(json.dumps(
                    {"kind": "event", "name": "telemetry.dropped",
                     "t": self._clock(),
                     "attrs": {"dropped": self.dropped}}) + "\n")
            self._closed = True
            if self._fh is not None:
                self._fh.close()
                self._fh = None


#: the process-current recorder; NULL outside a run
_current: Any = NULL


def current() -> Any:
    """The active run's Telemetry, or the no-op NULL outside a run."""
    return _current


def set_current(tel: Optional[Telemetry]) -> None:
    """Install (or with None, clear) the process-current recorder."""
    global _current
    _current = tel if tel is not None else NULL
