"""Network-event trace recorder (the ``--tcpdump`` analog).

The reference captures client-port pcaps per node (db.clj:276-277);
in the simulated net the equivalent is a message-level event log:
client->node RPCs and node->node replication/vote traffic, each with
virtual timestamps and payload summaries, written to
``store/<run>/trace.jsonl``.

Events STREAM to the file as they happen (small write-behind buffer,
flushed every ``FLUSH_EVERY`` events and at close) instead of
accumulating up to ``max_events`` dicts in memory — a long faulted run
records millions of replication heartbeats, and the old in-memory list
was hundreds of MB of host RAM held until teardown. Per-kind counts and
the dropped total accumulate incrementally and surface in the run's
``results.json`` (``net-trace``) and on the serve run page.
"""

from __future__ import annotations

import json
from typing import Any, Optional

#: events buffered between file writes; small enough that a crashed
#: run loses at most this many tail events
FLUSH_EVERY = 2048


class NetTrace:
    """Append-only message trace; one dict per event.

    With ``path`` set, events stream to that file and are not retained
    in memory. Without a path (unit-test / REPL use), events collect in
    ``self.events`` and ``to_jsonl()`` renders them, as before.
    """

    def __init__(self, loop, max_events: int = 2_000_000,
                 path: Optional[str] = None):
        self.loop = loop
        self.path = path
        self.events: list[dict] = []
        self.n = 0
        self.dropped = 0
        self.max_events = max_events
        self.by_kind: dict[str, int] = {}
        self._buf: list[str] = []
        self._fh = None
        self._closed = False

    def record(self, kind: str, src: str, dst: str, **info: Any) -> None:
        if self.n >= self.max_events:
            self.dropped += 1
            return
        e = {"t": self.loop.now, "kind": kind, "src": src, "dst": dst,
             **info}
        self.n += 1
        self.by_kind[kind] = self.by_kind.get(kind, 0) + 1
        if self.path is None:
            self.events.append(e)
            return
        self._buf.append(json.dumps(e, default=repr))
        if len(self._buf) >= FLUSH_EVERY:
            self._flush()

    def _flush(self) -> None:
        if not self._buf:
            return
        if self._fh is None:
            self._fh = open(self.path, "w")
        self._fh.write("\n".join(self._buf) + "\n")
        self._buf.clear()

    def counts(self) -> dict:
        return dict(self.by_kind)

    def summary(self) -> dict:
        """The results.json / serve surface: totals, dropped, per-kind."""
        return {"events": self.n, "dropped": self.dropped,
                "counts": dict(sorted(self.by_kind.items()))}

    def close(self) -> None:
        """Flush the stream (appending the truncation marker the old
        format carried when events were dropped). Idempotent; a no-op
        for in-memory traces."""
        if self._closed or self.path is None:
            return
        self._closed = True
        if self.dropped:
            self._buf.append(json.dumps({"truncated": self.dropped}))
        self._flush()
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def to_jsonl(self) -> str:
        lines = [json.dumps(e, default=repr) for e in self.events]
        if self.dropped:
            lines.append(json.dumps({"truncated": self.dropped}))
        return "\n".join(lines)
