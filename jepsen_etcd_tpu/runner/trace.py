"""Network-event trace recorder (the ``--tcpdump`` analog).

The reference captures client-port pcaps per node (db.clj:276-277);
in the simulated net the equivalent is a message-level event log:
client->node RPCs and node->node replication/vote traffic, each with
virtual timestamps and payload summaries, written to
``store/<run>/trace.jsonl``.
"""

from __future__ import annotations

import json
from typing import Any


class NetTrace:
    """Append-only in-memory message trace; one dict per event."""

    def __init__(self, loop, max_events: int = 2_000_000):
        self.loop = loop
        self.events: list[dict] = []
        self.dropped = 0
        self.max_events = max_events

    def record(self, kind: str, src: str, dst: str, **info: Any) -> None:
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return
        self.events.append({"t": self.loop.now, "kind": kind,
                            "src": src, "dst": dst, **info})

    def counts(self) -> dict:
        out: dict = {}
        for e in self.events:
            out[e["kind"]] = out.get(e["kind"], 0) + 1
        return out

    def to_jsonl(self) -> str:
        lines = [json.dumps(e, default=repr) for e in self.events]
        if self.dropped:
            lines.append(json.dumps({"truncated": self.dropped}))
        return "\n".join(lines)
