"""Fleet campaign driver: seeds x workloads x nemeses over a process pool.

The reference's ``test-all`` sweeps its matrix serially
(etcd.clj:226-244); at fleet scale the sweep IS the workload, so this
driver fans the expanded matrix over a bounded pool of spawned worker
processes (one ``run_test`` per spec, per-run store dirs under the
shared base — ``make_store_dir`` claims ids atomically) and, when the
checker service is on, hosts ONE device-owning
``runner/checker_service.CheckerService`` whose socket every worker's
checker submits packed histories to — device dispatches are paid per
(bucket, width, tick), not per run (PERF.md §campaign has the
amortization accounting).

Workers are SPAWNED, never forked: every worker initializes its own
jax runtime, and forking a process with live device state (or live
threads — the service, telemetry writers) is undefined. The spawn
import cost (~seconds) is paid once per pool slot and amortizes over
the campaign.

Artifacts: the campaign itself owns a store dir
(``store/<name>/<id>/``) holding ``campaign.json`` (per-run rows +
failure summary + service stats) and ``telemetry.jsonl``
(``campaign.*`` counters, one ``campaign.run`` event per run, and the
service's counters folded in at the end). ``serve.py /aggregate``
reads these for the perf-trends-across-campaigns section.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import socket
import threading
import time
from collections import deque
from typing import Optional

from .store import _scrub, link_latest, make_store_dir
from .telemetry import Hist, Telemetry

logger = logging.getLogger("jepsen_etcd_tpu.campaign")

#: datagram backlog bound for the live collector: past it records are
#: shed and counted (live.dropped) — the fleet never blocks on the
#: dashboard
LIVE_QUEUE_MAX = 8192

#: live.json snapshot cadence (seconds)
LIVE_SNAPSHOT_S = 0.5


class LiveCollector:
    """Bounded, lossy aggregation of the fleet's live telemetry.

    Campaign workers and the checker service stream their records as
    JSON datagrams to an AF_UNIX socket this collector owns (see
    ``Telemetry(sink=...)``); two threads (receive -> bounded queue ->
    fold) turn them into an atomic ``live.json`` snapshot that
    ``serve.py /live`` tails over SSE. Everything here is best-effort:
    a slow collector sheds datagrams (counted), a torn or non-JSON
    datagram is counted and skipped, and the campaign's correctness
    artifacts never depend on this path. All shared state is mutated
    under ``_cv`` only.
    """

    def __init__(self, cdir: str, trace: Optional[str] = None):
        self.dir = cdir
        self.path = os.path.join(cdir, "live.sock")
        self.json_path = os.path.join(cdir, "live.json")
        self.trace = trace
        self._cv = threading.Condition()
        self._queue: deque = deque()
        self._stopped = False
        self.records = 0
        self.dropped = 0
        self.bad = 0
        # fold state (all under _cv): per-run progress, service
        # occupancy, summed counters, merged histograms
        self._runs: dict = {}
        self._service: dict = {}
        self._counters: dict = {}
        self._hists: dict = {}
        self._sock: Optional[socket.socket] = None
        self._threads: list = []

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "LiveCollector":
        s = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        try:
            os.unlink(self.path)
        except OSError:
            pass
        s.bind(self.path)
        s.settimeout(0.25)  # poll the stop flag; close() never hangs
        try:  # a deeper kernel buffer before the queue bound engages
            s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 1 << 20)
        except OSError:
            pass
        recv = threading.Thread(target=self._recv_loop,
                                name="campaign-live-recv", daemon=True)
        fold = threading.Thread(target=self._fold_loop,
                                name="campaign-live-fold", daemon=True)
        with self._cv:
            self._sock = s
            self._threads = [recv, fold]
        recv.start()
        fold.start()
        self._snapshot()  # /live has something to show immediately
        return self

    def close(self) -> dict:
        """Stop both threads, write the final ``done`` snapshot, and
        return ``{records, dropped, bad}``."""
        with self._cv:
            if not self._stopped:
                self._stopped = True
                self._cv.notify_all()
            threads = list(self._threads)
            sock = self._sock
        for t in threads:
            t.join(timeout=10)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._snapshot(done=True)
        try:
            os.unlink(self.path)
        except OSError:
            pass
        with self._cv:
            return {"records": self.records, "dropped": self.dropped,
                    "bad": self.bad}

    # -- receive side --------------------------------------------------------
    def _recv_loop(self) -> None:
        while True:
            with self._cv:
                if self._stopped:
                    return
                sock = self._sock
            try:
                data, _ = sock.recvfrom(1 << 20)
            except socket.timeout:
                continue
            except OSError:
                return  # socket closed by close()
            with self._cv:
                if len(self._queue) >= LIVE_QUEUE_MAX:
                    self.dropped += 1
                else:
                    self._queue.append(data)
                    self._cv.notify_all()

    # -- fold side -----------------------------------------------------------
    def _fold_loop(self) -> None:
        last_snap = 0.0
        while True:
            with self._cv:
                if not self._queue and not self._stopped:
                    # bounded wait, not until-work: idle campaigns
                    # still refresh the snapshot's heartbeat
                    self._cv.wait(timeout=LIVE_SNAPSHOT_S)
                if self._stopped and not self._queue:
                    return
                batch = list(self._queue)
                self._queue.clear()
            for data in batch:
                try:
                    rec = json.loads(data.decode("utf-8", "replace"))
                    if not isinstance(rec, dict):
                        raise ValueError("record is not an object")
                except (ValueError, UnicodeDecodeError):
                    with self._cv:
                        self.bad += 1
                    continue
                self._fold(rec)
            now = time.monotonic()
            if now - last_snap >= LIVE_SNAPSHOT_S:
                self._snapshot()
                last_snap = now

    def _fold(self, rec: dict) -> None:
        # _cv is a Condition over an RLock, so this nests under the
        # drain loop's hold too
        kind = rec.get("kind")
        name = rec.get("name") or ""
        trace = rec.get("trace")
        with self._cv:
            self.records += 1
            if kind == "span":
                if trace is not None:
                    st = self._runs.setdefault(trace, {"spans": 0})
                    st["spans"] += 1
                    st["last"] = name
                    if name.startswith("phase:"):
                        st["phase"] = name[len("phase:"):]
                if name == "service.tick":
                    attrs = rec.get("attrs") or {}
                    self._service = {
                        "ticks": self._service.get("ticks", 0) + 1,
                        "packs": attrs.get("packs"),
                        "requests": attrs.get("requests"),
                        "groups": attrs.get("groups"),
                        "runs": attrs.get("runs"),
                        "device": attrs.get("device"),
                        "placement": attrs.get("placement"),
                        "sharded": attrs.get("sharded"),
                    }
                dur = rec.get("dur_s")
                if name in ("wgl.check_packed", "stream.chunk",
                            "service.tick") and isinstance(dur,
                                                           (int, float)):
                    self._hists.setdefault(name, Hist()).record(dur)
            elif kind == "counter":
                v = rec.get("value")
                if isinstance(v, (int, float)):
                    self._counters[name] = \
                        self._counters.get(name, 0) + v
            elif kind == "hist":
                # a run (or the service) closed and flushed its
                # histograms: merge them so /live sparklines cover op
                # latencies too
                key = ("op.latency.*" if name.startswith("op.latency.")
                       else name)
                self._hists.setdefault(key, Hist()).merge(
                    Hist.from_dict(rec))
            elif kind == "event" and name == "campaign.run" \
                    and trace is not None:
                st = self._runs.setdefault(trace, {"spans": 0})
                st.update(rec.get("attrs") or {})

    def note_row(self, row: dict) -> None:
        """Driver-side fold of a finished row (authoritative status —
        works even when every datagram was shed)."""
        trace = row.get("trace")
        if trace is None:
            return
        with self._cv:
            st = self._runs.setdefault(trace, {"spans": 0})
            st["status"] = row.get("status")
            st["valid"] = row.get("valid")
            st["index"] = row.get("index")
            if row.get("host") is not None:
                st["host"] = row.get("host")
        self._snapshot()

    def _snapshot(self, done: bool = False) -> None:
        """Atomically publish live.json (tmp + rename; readers never
        see a torn file)."""
        with self._cv:
            snap = {
                "campaign": self.trace,
                "t": time.time(),
                "records": self.records,
                "dropped": self.dropped,
                "bad": self.bad,
                "runs": {k: dict(v) for k, v in self._runs.items()},
                "service": dict(self._service),
                "counters": dict(self._counters),
                "hists": {k: h.to_dict()
                          for k, h in self._hists.items()},
                "done": done,
            }
        tmp = self.json_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(snap, f, default=repr)
            os.replace(tmp, self.json_path)
        except OSError:
            pass  # dashboard-only artifact: never fail the campaign


def campaign_specs(base_opts: dict, workloads: list,
                   nemeses: list, runs_per_cell: int = 1,
                   seed0: int = 0) -> list[dict]:
    """Expand the test-all matrix into one spec per run. Seeds are
    distinct across the whole campaign (seed0 + running index) so no
    two runs replay the same history."""
    specs = []
    for nem in nemeses:
        for wl in workloads:
            for i in range(runs_per_cell):
                opts = dict(base_opts)
                opts.update({"workload": wl, "nemesis": list(nem),
                             "seed": seed0 + len(specs)})
                specs.append({"index": len(specs), "opts": opts})
    return specs


#: the campaign-row histogram groups (ISSUE 14 acceptance: per-row
#: p50/p95/p99 for gen, check, and queue-wait): label -> matcher over
#: the run summary's hist names
_ROW_HIST_GROUPS = (
    ("gen", lambda n: n.startswith("op.latency.")),
    ("check", lambda n: n == "wgl.check_packed"),
    ("queue_wait", lambda n: n == "service.queue_wait_s"),
)


def _row_hists(tel_sum: dict) -> tuple[dict, dict]:
    """(hists, p) for one run's telemetry summary: per-group merged
    sparse histograms and their [p50, p95, p99] triples. Groups with
    no observations are omitted."""
    hists = tel_sum.get("hists") or {}
    out_h: dict = {}
    out_p: dict = {}
    for label, match in _ROW_HIST_GROUPS:
        ds = [d for n, d in hists.items() if match(n)]
        if not ds:
            continue
        h = Hist()
        for d in ds:
            h.merge(Hist.from_dict(d))
        d = h.to_dict()
        out_h[label] = d
        out_p[label] = [d["p50"], d["p95"], d["p99"]]
    return out_h, out_p


def _row_net(counters: dict) -> dict:
    """The lossy-link diagnosis triple surfaced on /aggregate."""
    return {"dropped_chunks": int(counters.get("net.dropped_chunks", 0)),
            "accept_errors": int(counters.get("net.accept_errors", 0)),
            "delayed_bytes": int(counters.get("net.delayed_bytes", 0))}


def _pool_run(spec: dict) -> dict:
    """One campaign run, executed inside a pool worker (top-level so
    spawn can pickle it by module path). Returns a compact summary row
    — never the history — so result transfer stays cheap."""
    opts = dict(spec["opts"])
    row: dict = {"index": spec["index"], "workload": opts.get("workload"),
                 "nemesis": opts.get("nemesis"), "seed": opts.get("seed"),
                 "trace": opts.get("trace_id"),
                 # histories from a live cluster are observed, not
                 # generated — no generator epoch applies there
                 "gen-epoch": (None if opts.get("client_type")
                               in ("http", "grpc") else "epoch-v1")}
    try:
        from ..compose import etcd_test
        from .test_runner import run_test
        test = etcd_test(opts)
        out = run_test(test)
    except NotImplementedError as e:
        row.update(status="skipped", error=str(e))
        return row
    except Exception as e:  # a crashed run must not kill the sweep
        logger.exception("campaign run %s failed", spec["index"])
        row.update(status="error", error=repr(e))
        return row
    tel = (out.get("results") or {}).get("telemetry") or {}
    counters = tel.get("counters") or {}
    phases = tel.get("phases") or {}
    hists, percentiles = _row_hists(tel)
    row.update(
        status="done", valid=out["valid?"], dir=out["dir"],
        ops=len(out["history"]), wall_s=round(out["wall-seconds"], 3),
        gen_ops_per_s=counters.get("generate.ops_per_s"),
        check_s=round(phases.get("check", 0.0), 4),
        dispatches=int(counters.get("wgl.dispatches", 0)
                       + counters.get("mxu.dispatches", 0)),
        service_fallbacks=int(counters.get("service.fallback", 0)),
        service_shipped=int(counters.get("service.shipped", 0)),
        service_queue_wait_s=round(
            counters.get("service.queue_wait_s", 0.0), 6),
        engines={k[len("engine."):]: v for k, v in counters.items()
                 if k.startswith("engine.")},
        net=_row_net(counters),
        hists=hists, p=percentiles,
    )
    return row


def _batchable(opts: dict) -> bool:
    """True when the batched generator (simbatch/) can serve this
    spec: an epoch-v2 (lockstep numpy) or epoch-v3 (jitted device) sim
    run of a supported workload — generate_for_opts routes between the
    two engines by the declared epoch. Live clusters produce observed
    histories (no generator epoch), and --stream/--soak runs
    interleave generation with the run itself, so all of those fall
    back to the epoch-v1 event loop."""
    if opts.get("gen_epoch") not in ("epoch-v2", "epoch-v3"):
        return False
    if opts.get("client_type") in ("http", "grpc"):
        return False
    if opts.get("db_mode") not in (None, "sim"):
        return False
    if opts.get("soak") or opts.get("stream"):
        return False
    from ..simbatch import supports
    return supports(opts.get("workload", "register"))


def _run_batched_cell(cell_specs: list, tel: Telemetry,
                      genbatch: dict) -> list:
    """One batched-generator cell: every spec in ``cell_specs`` shares
    a (workload, nemesis) point of the matrix, so their seeds generate
    in ONE lockstep columnar pass; each history then gets the normal
    per-run epilogue (checker, store dir, artifacts) in this process.
    Returns one summary row per spec, same shape as ``_pool_run``."""
    import time as wall_time

    from ..compose import etcd_test
    from ..simbatch import generate_for_opts
    from . import telemetry
    from .store import make_store_dir
    from .test_runner import _analyze_and_save, _make_telemetry

    seeds = [int(s["opts"].get("seed", 0)) for s in cell_specs]
    g0 = wall_time.time()
    gen = generate_for_opts(dict(cell_specs[0]["opts"]), seeds)
    gen_wall = wall_time.time() - g0
    agg = round(gen["events"] / max(gen_wall, 1e-9), 1)
    tel.counter("genbatch.cells")
    tel.counter("genbatch.seeds", len(seeds))
    tel.counter("genbatch.steps", gen["steps"])
    tel.counter("genbatch.events", gen["events"])
    tel.counter("genbatch.compactions", gen["compactions"])
    tel.counter("genbatch.ops_per_s", agg, mode="max")
    genbatch["cells"] += 1
    genbatch["seeds"] += len(seeds)
    genbatch["events"] += gen["events"]
    genbatch["ops_per_s"] = max(genbatch["ops_per_s"], agg)
    genbatch["epoch"] = gen["epoch"]
    rows = []
    for spec, history in zip(cell_specs, gen["histories"]):
        opts = dict(spec["opts"])
        row: dict = {"index": spec["index"],
                     "workload": opts.get("workload"),
                     "nemesis": opts.get("nemesis"),
                     "seed": opts.get("seed"),
                     "trace": opts.get("trace_id"),
                     "gen-epoch": gen["epoch"]}
        t0 = wall_time.time()
        run_tel = None
        try:
            test = etcd_test(opts)
            test["cluster"] = None
            store_dir = make_store_dir(opts.get("store_base", "store"),
                                       test.get("name", "test"))
            test["store_dir"] = store_dir
            run_tel = _make_telemetry(test, store_dir)
            cols = history.columns
            sim_seconds = (float(cols.time[-1]) / 1e9 if len(cols)
                           else 0.0)
            out = _analyze_and_save(test, history, store_dir,
                                    cluster=None, task_leak=None,
                                    sim_seconds=sim_seconds, t0=t0,
                                    node_logs={})
        except Exception as e:  # a crashed run must not kill the cell
            logger.exception("batched campaign run %s failed",
                             spec["index"])
            row.update(status="error", error=repr(e))
            rows.append(row)
            continue
        finally:
            telemetry.set_current(None)
            if run_tel is not None:
                run_tel.close()
        tel_sum = (out.get("results") or {}).get("telemetry") or {}
        counters = tel_sum.get("counters") or {}
        phases = tel_sum.get("phases") or {}
        hists, percentiles = _row_hists(tel_sum)
        row.update(
            status="done", valid=out["valid?"], dir=out["dir"],
            ops=len(out["history"]),
            wall_s=round(out["wall-seconds"], 3),
            gen_ops_per_s=agg,
            check_s=round(phases.get("check", 0.0), 4),
            dispatches=int(counters.get("wgl.dispatches", 0)
                           + counters.get("mxu.dispatches", 0)),
            service_fallbacks=int(counters.get("service.fallback", 0)),
            service_shipped=int(counters.get("service.shipped", 0)),
            service_queue_wait_s=round(
                counters.get("service.queue_wait_s", 0.0), 6),
            engines={k[len("engine."):]: v for k, v in counters.items()
                     if k.startswith("engine.")},
            net=_row_net(counters),
            hists=hists, p=percentiles,
        )
        rows.append(row)
    return rows


def _expected_pass(workload: str) -> bool:
    from ..workloads import WORKLOADS_EXPECTED_TO_PASS
    return workload in WORKLOADS_EXPECTED_TO_PASS


def _tally_row(tel: Telemetry, row: dict) -> Optional[tuple]:
    """Count one finished row into the campaign telemetry; returns a
    failure tuple when the row should fail the campaign (the test-all
    exit-code contract: expected-to-pass workloads must pass; sweeps
    record skips and move on)."""
    status = row.get("status")
    tel.event("campaign.run", workload=row.get("workload"),
              nemesis=",".join(row.get("nemesis") or []),
              seed=row.get("seed"), status=status,
              valid=row.get("valid"), host=row.get("host"))
    if status == "skipped":
        tel.counter("campaign.skipped")
        return None
    if status == "error":
        tel.counter("campaign.errors")
        return (row.get("workload"), row.get("nemesis"),
                row.get("error"))
    tel.counter("campaign.completed")
    if row.get("valid") is not True and _expected_pass(row["workload"]):
        tel.counter("campaign.failed")
        return (row["workload"], row["nemesis"], row.get("valid"))
    return None


def run_campaign(specs: list[dict], *, pool: int = 4,
                 service: bool = True, service_tick_s: float = 0.05,
                 store_base: str = "store", name: str = "campaign",
                 start_method: str = "spawn",
                 live: bool = True,
                 hosts=None,
                 on_row=None) -> dict:
    """Run a campaign: every spec through the pool, one shared checker
    service (optional), one summary. ``pool=0`` runs specs inline in
    this process (the bench serial baseline). Returns the summary dict
    also written to ``<campaign dir>/campaign.json``.

    The campaign mints a trace id (``<name>-<dir id>``); each run gets
    ``<campaign trace>.r<index>`` stamped on every telemetry record,
    and the service carries ``<campaign trace>.svc`` — the artifacts
    join across processes by those ids. With ``live=True`` (default) a
    :class:`LiveCollector` aggregates the fleet's records into
    ``live.json`` for serve.py's ``/live`` page as the campaign runs.

    ``hosts`` switches the fan-out plane from a local process pool to
    the multi-host topology (ROADMAP direction #4): an int spawns that
    many local worker-agent processes (named ``host1..hostN`` — CI's
    faked fleet over loopback TCP), a list of names does the same per
    name. The checker service then listens on TCP with a
    campaign-minted shared-secret token, every agent's runs ship their
    device checks to it cross-host (attributed per host by the
    JET-HOST preamble), and rows carry the host that ran them —
    ``service.host_submitted.<host>`` vs the rows' summed
    ``service_shipped`` is the cross-host ledger."""
    t0 = time.monotonic()
    cdir = make_store_dir(store_base, name)
    trace = f"{name}-{os.path.basename(cdir)}"
    tel = Telemetry(os.path.join(cdir, "telemetry.jsonl"), trace=trace)
    if isinstance(hosts, int):
        hosts = [f"host{i + 1}" for i in range(hosts)] if hosts else None
    # the fleet auth token: minted per campaign, shared with the
    # service and every spawned agent via env — never argv, never disk
    token = hashlib.sha256(
        f"{trace}-{os.getpid()}".encode()).hexdigest()[:16] \
        if hosts else None
    svc = None
    svc_tel = None
    collector = None
    agent_pool = None
    failures: list = []
    rows: list = [None] * len(specs)
    service_stats = None
    try:
        if live:
            try:
                collector = LiveCollector(cdir, trace=trace).start()
            except OSError:
                logger.warning("live collector unavailable; campaign "
                               "continues without /live", exc_info=True)
                collector = None
            if collector is not None:
                try:
                    # register as a live-polling candidate so serve's
                    # SSE tick stats this dir instead of listdir-ing
                    # the whole store
                    from .store_index import note_live
                    note_live(cdir)
                except Exception:
                    logger.debug("live index registration failed",
                                 exc_info=True)
        if service:
            from .checker_service import CheckerService
            # the service gets its own on-disk stream (service.jsonl in
            # the campaign dir): tick spans carry the contributing run
            # trace ids, which summaries don't preserve
            svc_tel = Telemetry(
                os.path.join(cdir, "service.jsonl"),
                trace=f"{trace}.svc", parent=trace,
                sink=None if collector is None else collector.path)
            # hosts mode raises the TCP listener too: agents are other
            # processes, so unix-socket reach is not enough — and the
            # token gates every cross-host frame
            svc = CheckerService(tick_s=service_tick_s, tel=svc_tel,
                                 tcp=bool(hosts),
                                 auth_token=token).start()
        run_specs = []
        for i, s in enumerate(specs):
            s = dict(s)
            s.setdefault("index", i)
            opts = dict(s["opts"])
            # runs store as siblings of the campaign dir (same base),
            # so the serve.py run index and rotation see them
            opts.setdefault("store_base", store_base)
            opts["trace_id"] = f"{trace}.r{s['index']}"
            opts["trace_parent"] = trace
            if collector is not None:
                opts["live_sink"] = collector.path
            if svc is not None:
                # agents are separate hosts (in CI: separate
                # processes), so they dial the TCP endpoint; the
                # single-host pool keeps the unix socket
                opts["checker_service"] = (svc.tcp_endpoint if hosts
                                           else svc.path)
                if token:
                    opts["checker_service_token"] = token
            s["opts"] = opts
            run_specs.append(s)
        tel.counter("campaign.runs", len(run_specs))
        # epoch-v2 specs the batched generator can serve leave the pool
        # entirely: grouped by (workload, nemesis) cell, each cell's
        # seeds generate in one lockstep columnar pass in THIS process,
        # then check/save per run. Everything else (live, unsupported
        # workload, stream/soak, epoch-v1) takes the pool as before.
        cells: dict = {}
        pooled = []
        if any(_batchable(s["opts"]) for s in run_specs):
            from ..simbatch import BatchConfig
        for s in run_specs:
            if _batchable(s["opts"]):
                # the full config identity, not just (workload,
                # nemesis): guided mutants perturb schedules/knobs
                # inside one matrix cell and must not be coalesced
                # into a neighbour's generate() call
                key = BatchConfig.from_opts(s["opts"]).cache_key()
                cells.setdefault(key, []).append(s)
            else:
                pooled.append(s)
        genbatch = {"cells": 0, "seeds": 0, "events": 0,
                    "ops_per_s": 0.0, "epoch": None}

        def _row_done(row: dict) -> None:
            rows[row["index"]] = row
            fail = _tally_row(tel, row)
            if fail is not None:
                failures.append(fail)
            if collector is not None:
                collector.note_row(row)
            if on_row is not None:
                on_row(row)

        with tel.span("campaign.sweep", runs=len(run_specs),
                      pool=pool, service=bool(svc)):
            for cell_specs in cells.values():
                for row in _run_batched_cell(cell_specs, tel, genbatch):
                    _row_done(row)
            run_specs = pooled
            if hosts:
                from .host_agent import HostAgentPool
                agent_pool = HostAgentPool(token=token, tel=tel).start()
                agent_pool.spawn_local(hosts)
                ready = agent_pool.wait_ready(len(hosts), timeout=120.0)
                tel.counter("campaign.hosts", ready)
                if ready < len(hosts):
                    logger.warning(
                        "only %d/%d agents registered; stragglers' "
                        "specs will run on the rest or inline",
                        ready, len(hosts))
                agent_pool.run(run_specs, _row_done)
            elif pool and pool > 0:
                import concurrent.futures as cf
                import multiprocessing as mp
                ctx = mp.get_context(start_method)
                with cf.ProcessPoolExecutor(max_workers=pool,
                                            mp_context=ctx) as ex:
                    futs = [ex.submit(_pool_run, s) for s in run_specs]
                    for fut in cf.as_completed(futs):
                        _row_done(fut.result())
            else:
                for s in run_specs:
                    _row_done(_pool_run(s))
        if svc is not None:
            service_stats = svc.stats()
    finally:
        if agent_pool is not None:
            agent_pool.close()
        if svc is not None:
            svc.close()
            if service_stats is not None:
                # only known post-join: stats() ran pre-close
                service_stats["shutdown_leaked_threads"] = \
                    svc.shutdown_leaked_threads
        if svc_tel is not None:
            # flush the service stream (counters + hists) to disk; the
            # campaign owns this recorder, not the service
            svc_tel.close()
    if service_stats is not None:
        # fold the service's counters (service.* coalescing accounting
        # AND the wgl./mxu. dispatch counters its device work accrued)
        # into the campaign telemetry: one file proves the
        # dispatches-per-(bucket, width, tick) bar
        for cname, value in (service_stats.get("counters") or {}).items():
            tel.counter(cname, value,
                        mode="max" if cname in ("service.batch_occupancy",
                                                "service.device_occupancy")
                        else "sum")
    if collector is not None:
        lstats = collector.close()
        tel.counter("live.records", lstats["records"])
        tel.counter("live.dropped", lstats["dropped"] + lstats["bad"])
    # campaign-wide distributions: every row's sparse histograms merge
    # bucket-wise (the Hist contract), giving fleet p50/p95/p99 per
    # group next to the per-row triples
    merged: dict = {}
    for row in rows:
        for label, d in ((row or {}).get("hists") or {}).items():
            merged.setdefault(label, Hist()).merge(Hist.from_dict(d))
    hist_summaries = {label: h.to_dict() for label, h in merged.items()}
    # per-host fold: which host ran what, and the cross-host ledger's
    # producer side — each host's summed service_shipped must equal
    # the service's service.host_submitted.<host> (consumer side)
    by_host: dict = {}
    for row in rows:
        h = (row or {}).get("host")
        if h is None:
            continue
        st = by_host.setdefault(h, {"runs": 0, "shipped": 0,
                                    "fallbacks": 0})
        st["runs"] += 1
        st["shipped"] += int(row.get("service_shipped") or 0)
        st["fallbacks"] += int(row.get("service_fallbacks") or 0)
    summary = {
        "name": name, "dir": cdir, "count": len(specs),
        "pool": pool,
        "trace": trace,
        "valid?": not failures,
        "failures": failures,
        "genbatch": genbatch if genbatch["cells"] else None,
        "runs": rows,
        "hists": hist_summaries,
        "p": {label: [d["p50"], d["p95"], d["p99"]]
              for label, d in hist_summaries.items()},
        "hosts": by_host or None,
        "agent_requeues": (agent_pool.requeues
                           if agent_pool is not None else 0),
        "wall_s": round(time.monotonic() - t0, 3),
        "service": None if service_stats is None else {
            "socket": svc.path, **service_stats},
        "telemetry": tel.summary(),
    }
    with open(os.path.join(cdir, "campaign.json"), "w") as f:
        json.dump(_scrub(summary), f, indent=2, default=repr)
    tel.close()
    try:
        # fold the campaign into the store index (and retire its live
        # row) now that campaign.json and service.jsonl are complete
        from .store_index import record_campaign
        record_campaign(cdir)
    except Exception:
        logger.debug("campaign index write failed", exc_info=True)
    link_latest(cdir)
    logger.info(
        "campaign %s: %d runs, %d failures, %.1f s (dir %s)",
        name, len(specs), len(failures), summary["wall_s"], cdir)
    return summary
