"""Fleet campaign driver: seeds x workloads x nemeses over a process pool.

The reference's ``test-all`` sweeps its matrix serially
(etcd.clj:226-244); at fleet scale the sweep IS the workload, so this
driver fans the expanded matrix over a bounded pool of spawned worker
processes (one ``run_test`` per spec, per-run store dirs under the
shared base — ``make_store_dir`` claims ids atomically) and, when the
checker service is on, hosts ONE device-owning
``runner/checker_service.CheckerService`` whose socket every worker's
checker submits packed histories to — device dispatches are paid per
(bucket, width, tick), not per run (PERF.md §campaign has the
amortization accounting).

Workers are SPAWNED, never forked: every worker initializes its own
jax runtime, and forking a process with live device state (or live
threads — the service, telemetry writers) is undefined. The spawn
import cost (~seconds) is paid once per pool slot and amortizes over
the campaign.

Artifacts: the campaign itself owns a store dir
(``store/<name>/<id>/``) holding ``campaign.json`` (per-run rows +
failure summary + service stats) and ``telemetry.jsonl``
(``campaign.*`` counters, one ``campaign.run`` event per run, and the
service's counters folded in at the end). ``serve.py /aggregate``
reads these for the perf-trends-across-campaigns section.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Optional

from .store import _scrub, link_latest, make_store_dir
from .telemetry import Telemetry

logger = logging.getLogger("jepsen_etcd_tpu.campaign")


def campaign_specs(base_opts: dict, workloads: list,
                   nemeses: list, runs_per_cell: int = 1,
                   seed0: int = 0) -> list[dict]:
    """Expand the test-all matrix into one spec per run. Seeds are
    distinct across the whole campaign (seed0 + running index) so no
    two runs replay the same history."""
    specs = []
    for nem in nemeses:
        for wl in workloads:
            for i in range(runs_per_cell):
                opts = dict(base_opts)
                opts.update({"workload": wl, "nemesis": list(nem),
                             "seed": seed0 + len(specs)})
                specs.append({"index": len(specs), "opts": opts})
    return specs


def _pool_run(spec: dict) -> dict:
    """One campaign run, executed inside a pool worker (top-level so
    spawn can pickle it by module path). Returns a compact summary row
    — never the history — so result transfer stays cheap."""
    opts = dict(spec["opts"])
    row: dict = {"index": spec["index"], "workload": opts.get("workload"),
                 "nemesis": opts.get("nemesis"), "seed": opts.get("seed"),
                 # histories from a live cluster are observed, not
                 # generated — no generator epoch applies there
                 "gen-epoch": (None if opts.get("client_type")
                               in ("http", "grpc") else "epoch-v1")}
    try:
        from ..compose import etcd_test
        from .test_runner import run_test
        test = etcd_test(opts)
        out = run_test(test)
    except NotImplementedError as e:
        row.update(status="skipped", error=str(e))
        return row
    except Exception as e:  # a crashed run must not kill the sweep
        logger.exception("campaign run %s failed", spec["index"])
        row.update(status="error", error=repr(e))
        return row
    tel = (out.get("results") or {}).get("telemetry") or {}
    counters = tel.get("counters") or {}
    phases = tel.get("phases") or {}
    row.update(
        status="done", valid=out["valid?"], dir=out["dir"],
        ops=len(out["history"]), wall_s=round(out["wall-seconds"], 3),
        gen_ops_per_s=counters.get("generate.ops_per_s"),
        check_s=round(phases.get("check", 0.0), 4),
        dispatches=int(counters.get("wgl.dispatches", 0)
                       + counters.get("mxu.dispatches", 0)),
        service_fallbacks=int(counters.get("service.fallback", 0)),
        service_shipped=int(counters.get("service.shipped", 0)),
        engines={k[len("engine."):]: v for k, v in counters.items()
                 if k.startswith("engine.")},
    )
    return row


def _batchable(opts: dict) -> bool:
    """True when the batched lockstep generator (simbatch/) can serve
    this spec: an epoch-v2 sim run of a supported workload. Live
    clusters produce observed histories (no generator epoch), and
    --stream/--soak runs interleave generation with the run itself, so
    all of those fall back to the epoch-v1 event loop."""
    if opts.get("gen_epoch") != "epoch-v2":
        return False
    if opts.get("client_type") in ("http", "grpc"):
        return False
    if opts.get("db_mode") not in (None, "sim"):
        return False
    if opts.get("soak") or opts.get("stream"):
        return False
    from ..simbatch import supports
    return supports(opts.get("workload", "register"))


def _run_batched_cell(cell_specs: list, tel: Telemetry,
                      genbatch: dict) -> list:
    """One batched-generator cell: every spec in ``cell_specs`` shares
    a (workload, nemesis) point of the matrix, so their seeds generate
    in ONE lockstep columnar pass; each history then gets the normal
    per-run epilogue (checker, store dir, artifacts) in this process.
    Returns one summary row per spec, same shape as ``_pool_run``."""
    import time as wall_time

    from ..compose import etcd_test
    from ..simbatch import generate_for_opts
    from . import telemetry
    from .store import make_store_dir
    from .test_runner import _analyze_and_save, _make_telemetry

    seeds = [int(s["opts"].get("seed", 0)) for s in cell_specs]
    g0 = wall_time.time()
    gen = generate_for_opts(dict(cell_specs[0]["opts"]), seeds)
    gen_wall = wall_time.time() - g0
    agg = round(gen["events"] / max(gen_wall, 1e-9), 1)
    tel.counter("genbatch.cells")
    tel.counter("genbatch.seeds", len(seeds))
    tel.counter("genbatch.steps", gen["steps"])
    tel.counter("genbatch.events", gen["events"])
    tel.counter("genbatch.compactions", gen["compactions"])
    tel.counter("genbatch.ops_per_s", agg, mode="max")
    genbatch["cells"] += 1
    genbatch["seeds"] += len(seeds)
    genbatch["events"] += gen["events"]
    genbatch["ops_per_s"] = max(genbatch["ops_per_s"], agg)
    genbatch["epoch"] = gen["epoch"]
    rows = []
    for spec, history in zip(cell_specs, gen["histories"]):
        opts = dict(spec["opts"])
        row: dict = {"index": spec["index"],
                     "workload": opts.get("workload"),
                     "nemesis": opts.get("nemesis"),
                     "seed": opts.get("seed"),
                     "gen-epoch": gen["epoch"]}
        t0 = wall_time.time()
        run_tel = None
        try:
            test = etcd_test(opts)
            test["cluster"] = None
            store_dir = make_store_dir(opts.get("store_base", "store"),
                                       test.get("name", "test"))
            test["store_dir"] = store_dir
            run_tel = _make_telemetry(test, store_dir)
            cols = history.columns
            sim_seconds = (float(cols.time[-1]) / 1e9 if len(cols)
                           else 0.0)
            out = _analyze_and_save(test, history, store_dir,
                                    cluster=None, task_leak=None,
                                    sim_seconds=sim_seconds, t0=t0,
                                    node_logs={})
        except Exception as e:  # a crashed run must not kill the cell
            logger.exception("batched campaign run %s failed",
                             spec["index"])
            row.update(status="error", error=repr(e))
            rows.append(row)
            continue
        finally:
            telemetry.set_current(None)
            if run_tel is not None:
                run_tel.close()
        tel_sum = (out.get("results") or {}).get("telemetry") or {}
        counters = tel_sum.get("counters") or {}
        phases = tel_sum.get("phases") or {}
        row.update(
            status="done", valid=out["valid?"], dir=out["dir"],
            ops=len(out["history"]),
            wall_s=round(out["wall-seconds"], 3),
            gen_ops_per_s=agg,
            check_s=round(phases.get("check", 0.0), 4),
            dispatches=int(counters.get("wgl.dispatches", 0)
                           + counters.get("mxu.dispatches", 0)),
            service_fallbacks=int(counters.get("service.fallback", 0)),
            service_shipped=int(counters.get("service.shipped", 0)),
            engines={k[len("engine."):]: v for k, v in counters.items()
                     if k.startswith("engine.")},
        )
        rows.append(row)
    return rows


def _expected_pass(workload: str) -> bool:
    from ..workloads import WORKLOADS_EXPECTED_TO_PASS
    return workload in WORKLOADS_EXPECTED_TO_PASS


def _tally_row(tel: Telemetry, row: dict) -> Optional[tuple]:
    """Count one finished row into the campaign telemetry; returns a
    failure tuple when the row should fail the campaign (the test-all
    exit-code contract: expected-to-pass workloads must pass; sweeps
    record skips and move on)."""
    status = row.get("status")
    tel.event("campaign.run", workload=row.get("workload"),
              nemesis=",".join(row.get("nemesis") or []),
              seed=row.get("seed"), status=status,
              valid=row.get("valid"))
    if status == "skipped":
        tel.counter("campaign.skipped")
        return None
    if status == "error":
        tel.counter("campaign.errors")
        return (row.get("workload"), row.get("nemesis"),
                row.get("error"))
    tel.counter("campaign.completed")
    if row.get("valid") is not True and _expected_pass(row["workload"]):
        tel.counter("campaign.failed")
        return (row["workload"], row["nemesis"], row.get("valid"))
    return None


def run_campaign(specs: list[dict], *, pool: int = 4,
                 service: bool = True, service_tick_s: float = 0.05,
                 store_base: str = "store", name: str = "campaign",
                 start_method: str = "spawn",
                 on_row=None) -> dict:
    """Run a campaign: every spec through the pool, one shared checker
    service (optional), one summary. ``pool=0`` runs specs inline in
    this process (the bench serial baseline). Returns the summary dict
    also written to ``<campaign dir>/campaign.json``."""
    t0 = time.monotonic()
    cdir = make_store_dir(store_base, name)
    tel = Telemetry(os.path.join(cdir, "telemetry.jsonl"))
    svc = None
    failures: list = []
    rows: list = [None] * len(specs)
    service_stats = None
    try:
        if service:
            from .checker_service import CheckerService
            svc = CheckerService(tick_s=service_tick_s).start()
        run_specs = []
        for i, s in enumerate(specs):
            s = dict(s)
            s.setdefault("index", i)
            opts = dict(s["opts"])
            # runs store as siblings of the campaign dir (same base),
            # so the serve.py run index and rotation see them
            opts.setdefault("store_base", store_base)
            if svc is not None:
                opts["checker_service"] = svc.path
            s["opts"] = opts
            run_specs.append(s)
        tel.counter("campaign.runs", len(run_specs))
        # epoch-v2 specs the batched generator can serve leave the pool
        # entirely: grouped by (workload, nemesis) cell, each cell's
        # seeds generate in one lockstep columnar pass in THIS process,
        # then check/save per run. Everything else (live, unsupported
        # workload, stream/soak, epoch-v1) takes the pool as before.
        cells: dict = {}
        pooled = []
        for s in run_specs:
            if _batchable(s["opts"]):
                key = (s["opts"].get("workload"),
                       tuple(s["opts"].get("nemesis") or ()))
                cells.setdefault(key, []).append(s)
            else:
                pooled.append(s)
        genbatch = {"cells": 0, "seeds": 0, "events": 0,
                    "ops_per_s": 0.0, "epoch": None}
        with tel.span("campaign.sweep", runs=len(run_specs),
                      pool=pool, service=bool(svc)):
            for cell_specs in cells.values():
                for row in _run_batched_cell(cell_specs, tel, genbatch):
                    rows[row["index"]] = row
                    fail = _tally_row(tel, row)
                    if fail is not None:
                        failures.append(fail)
                    if on_row is not None:
                        on_row(row)
            run_specs = pooled
            if pool and pool > 0:
                import concurrent.futures as cf
                import multiprocessing as mp
                ctx = mp.get_context(start_method)
                with cf.ProcessPoolExecutor(max_workers=pool,
                                            mp_context=ctx) as ex:
                    futs = [ex.submit(_pool_run, s) for s in run_specs]
                    for fut in cf.as_completed(futs):
                        row = fut.result()
                        rows[row["index"]] = row
                        fail = _tally_row(tel, row)
                        if fail is not None:
                            failures.append(fail)
                        if on_row is not None:
                            on_row(row)
            else:
                for s in run_specs:
                    row = _pool_run(s)
                    rows[row["index"]] = row
                    fail = _tally_row(tel, row)
                    if fail is not None:
                        failures.append(fail)
                    if on_row is not None:
                        on_row(row)
        if svc is not None:
            service_stats = svc.stats()
    finally:
        if svc is not None:
            svc.close()
    if service_stats is not None:
        # fold the service's counters (service.* coalescing accounting
        # AND the wgl./mxu. dispatch counters its device work accrued)
        # into the campaign telemetry: one file proves the
        # dispatches-per-(bucket, width, tick) bar
        for cname, value in (service_stats.get("counters") or {}).items():
            tel.counter(cname, value,
                        mode="max" if cname == "service.batch_occupancy"
                        else "sum")
    summary = {
        "name": name, "dir": cdir, "count": len(specs),
        "pool": pool,
        "valid?": not failures,
        "failures": failures,
        "genbatch": genbatch if genbatch["cells"] else None,
        "runs": rows,
        "wall_s": round(time.monotonic() - t0, 3),
        "service": None if service_stats is None else {
            "socket": svc.path, **service_stats},
        "telemetry": tel.summary(),
    }
    with open(os.path.join(cdir, "campaign.json"), "w") as f:
        json.dump(_scrub(summary), f, indent=2, default=repr)
    tel.close()
    link_latest(cdir)
    logger.info(
        "campaign %s: %d runs, %d failures, %.1f s (dir %s)",
        name, len(specs), len(failures), summary["wall_s"], cdir)
    return summary
