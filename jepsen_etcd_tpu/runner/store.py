"""Per-test artifact store (the jepsen.store analog).

Layout: store/<test-name>/<seq-timestamp>/{history.jsonl, results.json,
test.json, timeline.html, latency-raw.png, rate.png, <node>/etcd.log},
with store/<test-name>/latest symlinked to the newest run and
store/latest to the newest run overall.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import shutil
from typing import Any

logger = logging.getLogger("jepsen_etcd_tpu.store")

_seq = itertools.count()


def failure_signature(results: dict) -> str:
    """Canonical dedupe key for failing runs: the sorted set of
    ``checker=verdict`` entries that are not clean passes. THE single
    implementation — the dashboard (serve.py re-exports it as
    ``_failure_signature``), tel --coverage, shrink and the store
    index all import it from here, so index rows store the signature
    once and every reader agrees on it."""
    sig = []
    for k, v in results.items():
        if isinstance(v, dict) and "valid?" in v and \
                v.get("valid?") is not True:
            sig.append(f"{k}={v.get('valid?')}")
    return ", ".join(sorted(sig))

#: total store size cap: once exceeded, oldest runs are deleted after
#: each save (long test-all sweeps write GBs of artifacts and would
#: otherwise fill the disk). 0 disables rotation.
STORE_MAX_BYTES = int(os.environ.get(
    "JEPSEN_ETCD_TPU_STORE_MAX_BYTES", 2 * 1024 ** 3))


def _dir_size(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def rotate_store(base: str, keep_dir: str = None,
                 max_bytes: int = None) -> list[str]:
    """Delete oldest run dirs until the store fits under max_bytes.
    The run at keep_dir (the one just written) is never deleted;
    dangling `latest` symlinks left by a deletion are removed."""
    max_bytes = STORE_MAX_BYTES if max_bytes is None else max_bytes
    if max_bytes <= 0 or not os.path.isdir(base):
        return []
    keep = os.path.abspath(keep_dir) if keep_dir else None
    runs = []
    for test_name in sorted(os.listdir(base)):
        td = os.path.join(base, test_name)
        if os.path.islink(td) or not os.path.isdir(td):
            continue
        for run_id in sorted(os.listdir(td)):
            rd = os.path.join(td, run_id)
            if os.path.islink(rd) or not os.path.isdir(rd):
                continue
            try:
                mtime = os.path.getmtime(rd)
            except OSError:
                continue
            runs.append((mtime, rd, _dir_size(rd)))
    total = sum(s for _, _, s in runs)
    removed: list[str] = []
    for _, rd, size in sorted(runs):
        if total <= max_bytes:
            break
        if keep and os.path.abspath(rd) == keep:
            continue
        shutil.rmtree(rd, ignore_errors=True)
        total -= size
        removed.append(rd)
    if removed:
        try:
            from .store_index import mark_deleted
            mark_deleted(base, [os.path.relpath(rd, base)
                                for rd in removed])
        except Exception:
            logger.debug("index tombstone failed", exc_info=True)
        # WARNING with the list: rotation is on by default (2 GiB cap)
        # and may remove runs of OTHER tests under the store base —
        # pre-existing artifacts a user cares about deserve a loud,
        # attributable line (JEPSEN_ETCD_TPU_STORE_MAX_BYTES=0 opts out)
        logger.warning(
            "store rotation: removed %d old run dirs under %s "
            "(cap %d bytes; set JEPSEN_ETCD_TPU_STORE_MAX_BYTES=0 to "
            "disable): %s", len(removed), base, max_bytes,
            ", ".join(removed))
        for link in [os.path.join(base, "latest")] + [
                os.path.join(base, t, "latest")
                for t in os.listdir(base)
                if os.path.isdir(os.path.join(base, t))]:
            if os.path.islink(link) and not os.path.exists(link):
                try:
                    os.unlink(link)  # dangling after rotation
                except OSError:
                    pass
    return removed


def _next_run_id(tdir: str) -> int:
    # max+1, NOT count: rotation deletes the lowest-numbered (oldest)
    # runs, so a count could collide with a surviving higher id and
    # silently overwrite its artifacts. Suffixed ids ("00007-1234abcd",
    # the concurrent-creation escape hatch below) count by their
    # numeric prefix.
    existing = os.listdir(tdir) if os.path.isdir(tdir) else []
    ids = [int(e.split("-")[0]) for e in existing
           if e.split("-")[0].isdigit()]
    return max(ids) + 1 if ids else 0


def make_store_dir(base: str, test_name: str) -> str:
    """Create the next run dir. `latest` symlinks are NOT repointed here
    — the dir is made before the run executes (debug provenance needs
    its name), and a crashed run must not leave `latest` dangling at an
    empty dir; save_run repoints them once artifacts exist.

    Concurrency-safe: campaign pool workers (runner/campaign.py) create
    run dirs under one test name simultaneously, so the bare
    list-then-max id claim races. The claim itself is an ATOMIC
    ``os.mkdir`` (never ``exist_ok=True``, which would silently hand
    two runs the same artifact dir); a loser re-lists and retries, and
    after a few lost races appends a pid+uuid suffix that cannot
    collide."""
    import uuid
    tdir = os.path.join(base, test_name)
    os.makedirs(tdir, exist_ok=True)
    for attempt in range(8):
        run_id = f"{_next_run_id(tdir):05d}"
        if attempt >= 4:
            run_id += f"-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        path = os.path.join(tdir, run_id)
        try:
            os.mkdir(path)
            return path
        except FileExistsError:
            continue  # lost the claim race; re-list and retry
    raise OSError(f"could not claim a run dir under {tdir}")


def link_latest(store_dir: str) -> None:
    """Point store/<test>/latest and store/latest at a completed run."""
    run_id = os.path.basename(store_dir)
    test_dir = os.path.dirname(store_dir)
    base = os.path.dirname(test_dir)
    test_name = os.path.basename(test_dir)
    for link_base, target in ((test_dir, run_id),
                              (base, os.path.join(test_name, run_id))):
        link = os.path.join(link_base, "latest")
        try:
            if os.path.islink(link):
                os.unlink(link)
            os.symlink(target, link)
        except OSError:
            pass


def _scrub(x: Any):
    if isinstance(x, dict):
        return {str(k): _scrub(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_scrub(v) for v in x]
    if isinstance(x, (set, frozenset)):
        return sorted((_scrub(v) for v in x), key=repr)
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    return repr(x)


def save_run(store_dir: str, test: dict, history, results: dict,
             node_logs: dict) -> None:
    link_latest(store_dir)
    with open(os.path.join(store_dir, "history.jsonl"), "w") as f:
        f.write(history.to_jsonl())
    with open(os.path.join(store_dir, "results.json"), "w") as f:
        json.dump(_scrub(results), f, indent=2, default=repr)
    cfg = {k: v for k, v in test.items()
           if k not in ("cluster", "db", "client", "checker", "generator",
                        "nemesis", "final_generator")}
    with open(os.path.join(store_dir, "test.json"), "w") as f:
        json.dump(_scrub(cfg), f, indent=2, default=repr)
    for node, lines in node_logs.items():
        nd = os.path.join(store_dir, node)
        os.makedirs(nd, exist_ok=True)
        with open(os.path.join(nd, "etcd.log"), "w") as f:
            f.write("\n".join(lines))
    # index the run the moment its artifacts are complete: readers
    # (/aggregate, tel) fold the new row instead of re-walking the
    # tree. Best-effort — an index failure must never fail the save.
    try:
        from .store_index import record_run
        record_run(store_dir)
    except Exception:
        logger.debug("index write failed for %s", store_dir,
                     exc_info=True)
    # keep long sweeps from filling the disk; never touches this run
    rotate_store(os.path.dirname(os.path.dirname(store_dir)),
                 keep_dir=store_dir)
