"""Per-test artifact store (the jepsen.store analog).

Layout: store/<test-name>/<seq-timestamp>/{history.jsonl, results.json,
test.json, timeline.html, latency-raw.png, rate.png, <node>/etcd.log},
with store/<test-name>/latest symlinked to the newest run and
store/latest to the newest run overall.
"""

from __future__ import annotations

import itertools
import json
import os
from typing import Any

_seq = itertools.count()


def make_store_dir(base: str, test_name: str) -> str:
    """Create the next run dir. `latest` symlinks are NOT repointed here
    — the dir is made before the run executes (debug provenance needs
    its name), and a crashed run must not leave `latest` dangling at an
    empty dir; save_run repoints them once artifacts exist."""
    os.makedirs(base, exist_ok=True)
    existing = sorted(os.listdir(os.path.join(base, test_name))) \
        if os.path.isdir(os.path.join(base, test_name)) else []
    run_id = f"{len([e for e in existing if not e.startswith('latest')]):05d}"
    path = os.path.join(base, test_name, run_id)
    os.makedirs(path, exist_ok=True)
    return path


def link_latest(store_dir: str) -> None:
    """Point store/<test>/latest and store/latest at a completed run."""
    run_id = os.path.basename(store_dir)
    test_dir = os.path.dirname(store_dir)
    base = os.path.dirname(test_dir)
    test_name = os.path.basename(test_dir)
    for link_base, target in ((test_dir, run_id),
                              (base, os.path.join(test_name, run_id))):
        link = os.path.join(link_base, "latest")
        try:
            if os.path.islink(link):
                os.unlink(link)
            os.symlink(target, link)
        except OSError:
            pass


def _scrub(x: Any):
    if isinstance(x, dict):
        return {str(k): _scrub(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_scrub(v) for v in x]
    if isinstance(x, (set, frozenset)):
        return sorted((_scrub(v) for v in x), key=repr)
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    return repr(x)


def save_run(store_dir: str, test: dict, history, results: dict,
             node_logs: dict) -> None:
    link_latest(store_dir)
    with open(os.path.join(store_dir, "history.jsonl"), "w") as f:
        f.write(history.to_jsonl())
    with open(os.path.join(store_dir, "results.json"), "w") as f:
        json.dump(_scrub(results), f, indent=2, default=repr)
    cfg = {k: v for k, v in test.items()
           if k not in ("cluster", "db", "client", "checker", "generator",
                        "nemesis", "final_generator")}
    with open(os.path.join(store_dir, "test.json"), "w") as f:
        json.dump(_scrub(cfg), f, indent=2, default=repr)
    for node, lines in node_logs.items():
        nd = os.path.join(store_dir, node)
        os.makedirs(nd, exist_ok=True)
        with open(os.path.join(nd, "etcd.log"), "w") as f:
            f.write("\n".join(lines))
