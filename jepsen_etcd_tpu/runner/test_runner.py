"""The test harness: jepsen.core/run! re-designed for the hermetic runtime.

Sequence (SURVEY §3.1): DB setup on every node -> client open/setup per
worker -> generator interpretation (concurrent invokes + nemesis) ->
teardown -> checker.check over the recorded history -> artifacts.
"""

from __future__ import annotations

import logging
import time as wall_time
from typing import Any, Optional

from ..core.op import Op, NEMESIS
from ..core.history import History
from ..sut.cluster import Cluster, ClusterConfig
from ..sut.errors import SimError
from .sim import SimLoop, set_current_loop, current_loop
from .interpreter import interpret
from .store import make_store_dir, save_run
from . import telemetry
from .telemetry import Telemetry

logger = logging.getLogger("jepsen_etcd_tpu.run")


def _tally_generate(tel, history, wall_s: float) -> None:
    """Pinned generation counters (OBSERVABILITY.md §counters):
    ``generate.ops_per_s`` is recorded events per generate-phase wall
    second; the ``columns.*`` counters say whether (and how much of)
    the run's op stream was emitted as SoA columns alongside the
    dicts. mode="max" keeps each a plain value, not a running sum."""
    tel.counter("generate.ops_per_s",
                round(len(history) / max(wall_s, 1e-9), 1), mode="max")
    cols = getattr(history, "columns", None)
    if cols is None:
        tel.counter("columns.disabled", 1, mode="max")
        return
    tel.counter("columns.events", len(cols), mode="max")
    tel.counter("columns.keyed", int((cols.key_id >= 0).sum()),
                mode="max")
    tel.counter("columns.extras", len(cols.extras), mode="max")


def _make_telemetry(test: dict, store_dir: str):
    """Install the run's telemetry recorder (``--no-telemetry`` opts
    out; every other run writes telemetry.jsonl with no flag needed).
    A campaign-minted ``trace_id``/``trace_parent`` stamps every
    record, and ``live_sink`` streams them to the campaign's live
    collector socket (best-effort datagrams)."""
    if test.get("no_telemetry"):
        return None
    import os
    tel = Telemetry(os.path.join(store_dir, "telemetry.jsonl"),
                    trace=test.get("trace_id"),
                    parent=test.get("trace_parent"),
                    sink=test.get("live_sink"))
    telemetry.set_current(tel)
    return tel


def _make_stream(test: dict):
    """The run's streaming check feed (``--stream``), or None. Always
    clears a stale ``_stream`` hint map first: hints are one run's
    artifacts and must never leak into a re-used test dict."""
    test.pop("_stream", None)
    if not test.get("stream"):
        return None
    from .stream import StreamFeed
    return StreamFeed(test, chunk_ops=test.get("stream_chunk_ops") or 0)


def _finish_stream(stream, history) -> None:
    """Drain + join the feed and install its reuse hints; its own span
    so run reports separate residual finalize cost from phase:check."""
    if stream is None:
        return
    with telemetry.current().span("phase:stream-finalize",
                                  ops=len(history)) as sp:
        hints = stream.finish(history)
        sp.set(chunks=stream.chunks,
               hints=sorted(k for k in hints if k != "stats"))


class ClientPool:
    """Per-thread workload clients with jepsen's lifecycle: a worker whose
    process crashes (:info) gets a fresh client on its next op."""

    def __init__(self, test: dict):
        self.test = test
        self.proto = test["client"]
        self.by_thread: dict[int, tuple[int, Any]] = {}

    def node_for(self, process: int) -> str:
        nodes = self.test["nodes"]
        return nodes[process % len(nodes)]

    async def setup_initial(self, concurrency: int) -> None:
        for t in range(concurrency):
            c = self.proto.open(self.test, self.node_for(t))
            self.by_thread[t] = (t, c)
        # client setup! runs once per initial client before ops start
        for t in range(concurrency):
            await self.by_thread[t][1].setup(self.test)

    def client_for(self, process: int) -> Any:
        t = process % self.test["concurrency"]
        got = self.by_thread.get(t)
        if got is not None and got[0] == process:
            return got[1]
        if got is not None:
            got[1].close(self.test)
        c = self.proto.open(self.test, self.node_for(process))
        self.by_thread[t] = (process, c)
        return c

    async def teardown(self) -> None:
        for t, (p, c) in list(self.by_thread.items()):
            try:
                await c.teardown(self.test)
            finally:
                c.close(self.test)


#: client-side task-name prefixes; anything of these still live after
#: teardown + grace is a leaked client task (the sshj thread-leak
#: analog, support.clj:57-72)
_CLIENT_TASK_PREFIXES = ("rpc-", "keepalive-", "worker-", "evget")


def check_task_leaks(loop, where: str = "post-run") -> None:
    """Scan the SimLoop for live client tasks and throw, like the
    reference's pre-run sshj thread-leak scan (support.clj:57-72 throws
    :sshj-thread-leak with the offending stacks)."""
    leaked = [t.name for t in loop.tasks
              if not t.done and t.name.startswith(_CLIENT_TASK_PREFIXES)]
    if leaked:
        raise SimError("task-leak",
                       f"{where}: live client tasks: {sorted(leaked)[:16]} "
                       f"({len(leaked)} total)")


def run_test(test: dict) -> dict:
    """Run a composed test map; returns {valid?, results, history, dir}."""
    if test.get("client_type") in ("http", "grpc"):
        return run_test_live(test)
    seed = test.get("seed", 0)
    loop = SimLoop(seed=seed)
    set_current_loop(loop)
    t0 = wall_time.time()
    # The sim allocates millions of short-lived objects per run; cyclic GC
    # walks the ever-growing live graph (history, logs, WAL records) on
    # allocation thresholds and was measured costing 20-40% of generation
    # wall time, with multi-second run-to-run variance. Refcounting
    # reclaims the sim's true garbage; one collect at the end handles the
    # few cycles (tasks/coroutines).
    import gc
    gc_was_enabled = gc.isenabled()
    if gc_was_enabled:
        gc.disable()
    # store dir exists before ops run, so debug-mode provenance can embed
    # the run's dir name in written values (the reference's store/path is
    # likewise available during the run, append.clj:40)
    store_dir = make_store_dir(test.get("store_base", "store"),
                               test.get("name", "test"))
    test["store_dir"] = store_dir
    tel = _make_telemetry(test, store_dir)
    try:
        # thread the reference's SUT knobs from opts into the cluster
        # (etcd.clj:164,197-204 -> db.clj:88-99); an explicit
        # cluster_config still wins for tests that build their own
        cluster = Cluster(loop, list(test["nodes"]),
                          test.get("cluster_config") or ClusterConfig(
                              lazyfs=bool(test.get("lazyfs")),
                              snapshot_count=(
                                  100 if test.get("snapshot_count") is None
                                  else int(test["snapshot_count"])),
                              unsafe_no_fsync=bool(
                                  test.get("unsafe_no_fsync")),
                              corrupt_check=bool(
                                  test.get("corrupt_check"))))
        test["cluster"] = cluster
        if test.get("tcpdump"):
            # network-event trace (the --tcpdump analog, db.clj:276-277)
            # streaming straight to the run dir — events are never
            # buffered past the write-behind window
            import os
            from .trace import NetTrace
            cluster.tracer = NetTrace(
                loop, path=os.path.join(store_dir, "trace.jsonl"))
        db = test["db"]
        pool = ClientPool(test)
        nemesis_obj = test.get("nemesis")
        stream = _make_stream(test)

        async def invoke(process: int, op: Op) -> Op:
            client = pool.client_for(process)
            return await client.invoke(test, op)

        nemesis_invoke = None
        if nemesis_obj is not None:
            async def nemesis_invoke(op: Op) -> Op:
                return await nemesis_obj.invoke(test, op)

        async def main() -> History:
            tel_now = telemetry.current()
            logger.info("Setting up DB on %s", test["nodes"])
            with tel_now.span("phase:setup", nodes=len(test["nodes"])):
                await db.setup(test)
                if nemesis_obj is not None:
                    await nemesis_obj.setup(test)
                await pool.setup_initial(test["concurrency"])
            logger.info("Running generator")
            g0 = wall_time.time()
            with tel_now.span("phase:generate") as sp:
                h = await interpret(test, test["generator"], invoke,
                                    test["concurrency"],
                                    nemesis_invoke=nemesis_invoke,
                                    stream=stream)
                sp.set(ops=len(h))
            _tally_generate(tel_now, h, wall_time.time() - g0)
            with tel_now.span("phase:teardown"):
                await pool.teardown()
                if nemesis_obj is not None:
                    await nemesis_obj.teardown(test)
                await db.teardown(test)
                # grace: let closed clients' pumps observe closure and
                # timed-out rpcs cancel before the leak scan — derived
                # from the client timeout so raising TIMEOUT can't cause
                # spurious task-leak reports
                from .sim import sleep, SECOND
                from ..client.base import TIMEOUT
                await sleep(TIMEOUT + 1 * SECOND)
            return h

        history = loop.run_coro(main())
        _finish_stream(stream, history)
        sim_seconds = loop.now / 1e9
        # leak scan AFTER the run, recorded into results rather than
        # thrown — a leak must not destroy the run's artifacts (they're
        # the evidence needed to debug it)
        task_leak = None
        try:
            check_task_leaks(loop)
        except SimError as e:
            logger.error("task leak detected: %s", e)
            task_leak = str(e)
        set_current_loop(None)
        return _analyze_and_save(test, history, store_dir, cluster,
                                 task_leak, sim_seconds, t0)
    finally:
        set_current_loop(None)
        telemetry.set_current(None)
        if tel is not None:
            tel.close()
        if gc_was_enabled:
            # re-enable only, no collect: at this point the run's object
            # graph is still reachable through the caller's test dict, so
            # a collect here would scan millions of live objects and free
            # almost nothing. Ambient GC reclaims the cycles (tasks,
            # coroutine frames) once the caller drops the test.
            gc.enable()


def _analyze_and_save(test: dict, history, store_dir: str, cluster,
                      task_leak, sim_seconds: float, t0: float,
                      node_logs: Optional[dict] = None) -> dict:
    """Shared run epilogue: checker pass, task-leak / corrupt-check
    result merge, artifact save, summary line. cluster is None for live
    runs (no simulated nodes, no trace); node_logs overrides the
    cluster-derived logs (the local control plane collects its own)."""
    logger.info("Analyzing %d ops (history in %s)", len(history), store_dir)
    tel = telemetry.current()
    # service=True means device-bound checks may ship to a shared
    # campaign checker service — this run's check wall time then
    # includes socket round-trip + coalescing-tick queue wait, not
    # just local device work (see service.queue_wait_s on the
    # service side)
    with tel.span("phase:check", ops=len(history),
                  service=bool(test.get("checker_service"))):
        results = test["checker"].check(test, history,
                                        {"store_dir": store_dir})
    if task_leak is not None:
        results["task-leak"] = {"valid?": False, "error": task_leak}
        results["valid?"] = False
    if test.get("corrupt_check") and cluster is not None:
        # definite verdict from the runtime corruption monitor
        # (etcd.clj:164); the fatal alarm log line is independently
        # caught by the crash-pattern checker
        alarms = list(cluster.corruption_alarms)
        results["corrupt-check"] = {"valid?": not alarms, "alarms": alarms}
        if alarms:
            results["valid?"] = False
    if node_logs is None:
        node_logs = {} if cluster is None else {
            name: list(node.etcd_log)
            for name, node in cluster.nodes.items()}
    # the trace streams during the run; close it and fold its totals
    # into results BEFORE save_run so results.json carries them
    if cluster is not None and cluster.tracer is not None:
        cluster.tracer.close()
        results["net-trace"] = cluster.tracer.summary()
    if tel.enabled:
        results["telemetry"] = tel.summary()
    with tel.span("phase:save"):
        save_run(store_dir, test, history, results, node_logs)
    wall = wall_time.time() - t0
    logger.info("Run complete: valid?=%s (%d ops, %.1f sim-s, %.2f wall-s)",
                results.get("valid?"), len(history), sim_seconds, wall)
    return {"valid?": results.get("valid?"), "results": results,
            "history": history, "dir": store_dir,
            "sim-seconds": sim_seconds, "wall-seconds": wall}


def run_test_live(test: dict) -> dict:
    """Run a composed test against REAL etcd processes (the
    CLI-drives-a-real-cluster shape of etcd.clj:246-257).

    Same sequence as run_test, on a WallLoop (runner/wall.py): real
    time, real I/O, no simulated cluster. With --db live,
    test['nodes'] are endpoint URLs of an external cluster and faults
    are rejected upstream (compose): no control plane. With --db
    local, nodes are names, the LocalDb control plane (db/local.py)
    spawns and faults the processes, and the nemesis runs exactly as
    in the sim path."""
    from .wall import WallLoop
    loop = WallLoop(seed=test.get("seed", 0))
    set_current_loop(loop)
    t0 = wall_time.time()
    store_dir = make_store_dir(test.get("store_base", "store"),
                               test.get("name", "test"))
    test["store_dir"] = store_dir
    test["cluster"] = None  # cluster-reading checkers no-op on None
    tel = _make_telemetry(test, store_dir)
    try:
        db = test["db"]
        pool = ClientPool(test)
        nemesis_obj = test.get("nemesis")
        stream = _make_stream(test)

        async def invoke(process: int, op: Op) -> Op:
            client = pool.client_for(process)
            return await client.invoke(test, op)

        nemesis_invoke = None
        if nemesis_obj is not None:
            async def nemesis_invoke(op: Op) -> Op:
                return await nemesis_obj.invoke(test, op)

        async def main() -> History:
            tel_now = telemetry.current()
            logger.info("Awaiting live cluster %s", test["nodes"])
            with tel_now.span("phase:setup", nodes=len(test["nodes"])):
                await db.setup(test)
                if nemesis_obj is not None:
                    await nemesis_obj.setup(test)
                await pool.setup_initial(test["concurrency"])
            logger.info("Running generator (wall clock)")
            g0 = wall_time.time()
            with tel_now.span("phase:generate") as sp:
                h = await interpret(test, test["generator"], invoke,
                                    test["concurrency"],
                                    nemesis_invoke=nemesis_invoke,
                                    stream=stream)
                sp.set(ops=len(h))
            _tally_generate(tel_now, h, wall_time.time() - g0)
            with tel_now.span("phase:teardown"):
                await pool.teardown()
                if nemesis_obj is not None:
                    await nemesis_obj.teardown(test)
                await db.teardown(test)
                # grace before the leak scan: same TIMEOUT-derived
                # bound as the sim path, so in-flight rpcs and
                # keepalive pumps (interval LEASE_TTL/3 < TIMEOUT) can
                # observe closure
                from .sim import sleep, SECOND
                from ..client.base import TIMEOUT
                await sleep(TIMEOUT + 1 * SECOND)
            return h

        history = loop.run_coro(main())
        _finish_stream(stream, history)
        sim_seconds = loop.now / 1e9
        task_leak = None
        try:
            check_task_leaks(loop)
        except SimError as e:
            logger.error("task leak detected: %s", e)
            task_leak = str(e)
        set_current_loop(None)
        loop.shutdown()
        # local-mode node logs come from the control plane's per-node
        # capture files (db.clj:234-242); plain live mode has no shell
        # on the nodes, so its log_files() is empty
        return _analyze_and_save(test, history, store_dir, None,
                                 task_leak, sim_seconds, t0,
                                 node_logs=db.log_files(test))
    finally:
        set_current_loop(None)
        loop.shutdown()
        telemetry.set_current(None)
        if tel is not None:
            tel.close()


class _SharedDb:
    """One live cluster across soak windows: the inner db's setup runs
    on the first window only, per-window teardown is a no-op, and
    ``close()`` performs the real teardown after the last window.
    Everything else (client_url, fault delivery, log collection)
    forwards to the inner control plane."""

    def __init__(self, inner):
        self.inner = inner
        self._ready = False

    def __getattr__(self, name):
        return getattr(self.inner, name)

    async def setup(self, test: dict) -> None:
        if not self._ready:
            await self.inner.setup(test)
            self._ready = True

    async def teardown(self, test: dict) -> None:
        pass

    def close(self) -> None:
        if self._ready:
            self.inner.stop_all()


#: per-window register key-space stride: windows re-use the retained
#: cluster state, so a key checked in window w must never be generated
#: again in window w+1 (stale state would read as a false
#: linearizability violation)
SOAK_KEY_STRIDE = 100_000

#: soak net-fault kinds the schedule accepts (``kind`` or ``kind:arg``)
SOAK_NET_FAULTS = ("latency", "drop", "partition")


def _apply_soak_net_fault(plane, fault: str, nodes: list) -> None:
    """Program one window-long fault on the shared proxy plane.

    Spec is ``kind`` or ``kind:arg`` — ``latency[:delta-ms]`` (plus a
    fixed 10 ms jitter), ``drop[:probability]`` (per-chunk loss, every
    leg), ``partition`` (first node vs the rest, peer legs only).
    Unlike the per-window nemesis, these rules persist for the WHOLE
    window: degradation a start/stop generator cannot express.
    """
    kind, _, arg = fault.partition(":")
    if kind == "latency":
        plane.set_latency(float(arg or 40.0), 10.0)
    elif kind == "drop":
        plane.set_drop_prob(float(arg or 0.05))
    elif kind == "partition":
        plane.partition([[nodes[0]], list(nodes[1:])])
    else:
        raise ValueError(f"unknown soak net fault {fault!r}; "
                         f"kinds: {SOAK_NET_FAULTS}")


def run_soak(opts: dict, on_window=None) -> dict:
    """Sliding-window soak: check a long-running local cluster window
    by window with bounded memory (ISSUE 8 tentpole (c)).

    One shared control plane (``_SharedDb``) outlives every window;
    each window composes a fresh test with a rotated seed and register
    key offset, runs the normal live pipeline (streaming enabled by
    default so hints overlap generation), and is reduced to a summary
    dict immediately — the window's history is released before the
    next window generates, so memory is bounded by one window.

    ``soak_windows`` = 0 runs until interrupted (the CLI's soak mode);
    ``on_window(summary, out)`` sees each window's full result before
    release and may return truthy to stop the loop.
    """
    from ..compose import etcd_test
    base = dict(opts)
    base.pop("soak", None)
    windows_target = int(base.pop("soak_windows", 0) or 0)
    window_s = base.pop("soak_window_s", None)
    if base.get("client_type") not in ("http", "grpc"):
        raise ValueError(
            "soak mode checks a long-lived live cluster; use "
            "--client-type http/grpc with --db local (the fake-etcd "
            "stub works) or --db live")
    # long-lived network fault schedule: window w runs ENTIRELY under
    # schedule[w % len(schedule)], applied to the shared proxy plane
    # before the window starts and healed after it ends — the retained
    # cluster is what makes a whole-window fault meaningful
    net_faults = [f for f in (base.pop("soak_net_faults", None) or []) if f]
    if net_faults:
        if base.get("db_mode") != "local":
            raise ValueError(
                "soak net faults ride the userspace proxy plane: "
                "requires --db local")
        for f in net_faults:
            if f.partition(":")[0] not in SOAK_NET_FAULTS:
                raise ValueError(f"unknown soak net fault {f!r}; "
                                 f"kinds: {SOAK_NET_FAULTS}")
        base["net_proxy"] = True  # the plane must exist to program
    schedule = [None] + net_faults
    if base.get("db_mode") == "local" and not base.get("etcd_data_dir"):
        # windows >= 1 discard their freshly composed LocalDb; pin one
        # data root so the discards never mkdtemp roots of their own
        import tempfile
        base["etcd_data_dir"] = tempfile.mkdtemp(prefix="jepsen-soak-")
    # soak always streams: the window's pack/scan artifacts are ready
    # the moment generation ends, so per-window checking stays a
    # vectorized finalize (setdefault is not enough — the CLI threads
    # an explicit stream=False through opts_from_args)
    if not base.get("stream"):
        base["stream"] = True
    shared = None
    summaries: list[dict] = []
    all_valid = True
    w = 0
    try:
        while windows_target == 0 or w < windows_target:
            o = dict(base)
            if window_s:
                o["time_limit"] = window_s
            o["key_offset"] = (int(base.get("key_offset") or 0)
                               + w * SOAK_KEY_STRIDE)
            o["seed"] = int(base.get("seed") or 0) + w
            test = etcd_test(o)
            if shared is None:
                shared = _SharedDb(test["db"])
            test["db"] = shared
            test["name"] = f"{test['name']}-soak-w{w}"
            fault = schedule[w % len(schedule)]
            plane = getattr(shared, "plane", None)
            if fault is not None:
                if plane is None:
                    raise ValueError(
                        "soak net fault scheduled but the shared db "
                        "raised no proxy plane")
                _apply_soak_net_fault(plane, fault, sorted(o["nodes"]))
            try:
                out = run_test_live(test)
            finally:
                if fault is not None and plane is not None:
                    plane.heal()
            summary = {"window": w, "valid?": out["valid?"],
                       "soak-fault": fault,
                       "ops": len(out["history"]),
                       "dir": out["dir"],
                       "wall-seconds": out["wall-seconds"],
                       "key_offset": o["key_offset"],
                       "seed": o["seed"]}
            try:
                import resource
                summary["rss_peak_kb"] = resource.getrusage(
                    resource.RUSAGE_SELF).ru_maxrss
            except Exception:
                pass
            summaries.append(summary)
            all_valid = all_valid and out["valid?"] is True
            logger.info("soak window %d: valid?=%s (%d ops)",
                        w, out["valid?"], summary["ops"])
            stop = on_window(summary, out) if on_window is not None \
                else None
            # release the window: the summary is all that survives
            out = None
            test = None
            w += 1
            if stop:
                break
    finally:
        if shared is not None:
            shared.close()
    return {"valid?": all_valid, "windows": summaries, "count": w}
