"""The test harness: jepsen.core/run! re-designed for the hermetic runtime.

Sequence (SURVEY §3.1): DB setup on every node -> client open/setup per
worker -> generator interpretation (concurrent invokes + nemesis) ->
teardown -> checker.check over the recorded history -> artifacts.
"""

from __future__ import annotations

import logging
import time as wall_time
from typing import Any, Optional

from ..core.op import Op, NEMESIS
from ..core.history import History
from ..sut.cluster import Cluster, ClusterConfig
from .sim import SimLoop, set_current_loop, current_loop
from .interpreter import interpret
from .store import make_store_dir, save_run

logger = logging.getLogger("jepsen_etcd_tpu.run")


class ClientPool:
    """Per-thread workload clients with jepsen's lifecycle: a worker whose
    process crashes (:info) gets a fresh client on its next op."""

    def __init__(self, test: dict):
        self.test = test
        self.proto = test["client"]
        self.by_thread: dict[int, tuple[int, Any]] = {}

    def node_for(self, process: int) -> str:
        nodes = self.test["nodes"]
        return nodes[process % len(nodes)]

    async def setup_initial(self, concurrency: int) -> None:
        for t in range(concurrency):
            c = self.proto.open(self.test, self.node_for(t))
            self.by_thread[t] = (t, c)
        # client setup! runs once per initial client before ops start
        for t in range(concurrency):
            await self.by_thread[t][1].setup(self.test)

    def client_for(self, process: int) -> Any:
        t = process % self.test["concurrency"]
        got = self.by_thread.get(t)
        if got is not None and got[0] == process:
            return got[1]
        if got is not None:
            got[1].close(self.test)
        c = self.proto.open(self.test, self.node_for(process))
        self.by_thread[t] = (process, c)
        return c

    async def teardown(self) -> None:
        for t, (p, c) in list(self.by_thread.items()):
            try:
                await c.teardown(self.test)
            finally:
                c.close(self.test)


def run_test(test: dict) -> dict:
    """Run a composed test map; returns {valid?, results, history, dir}."""
    seed = test.get("seed", 0)
    loop = SimLoop(seed=seed)
    set_current_loop(loop)
    t0 = wall_time.time()
    try:
        cluster = Cluster(loop, list(test["nodes"]),
                          test.get("cluster_config") or ClusterConfig(
                              lazyfs=bool(test.get("lazyfs"))))
        test["cluster"] = cluster
        db = test["db"]
        pool = ClientPool(test)
        nemesis_obj = test.get("nemesis")

        async def invoke(process: int, op: Op) -> Op:
            client = pool.client_for(process)
            return await client.invoke(test, op)

        nemesis_invoke = None
        if nemesis_obj is not None:
            async def nemesis_invoke(op: Op) -> Op:
                return await nemesis_obj.invoke(test, op)

        async def main() -> History:
            logger.info("Setting up DB on %s", test["nodes"])
            await db.setup(test)
            if nemesis_obj is not None:
                await nemesis_obj.setup(test)
            await pool.setup_initial(test["concurrency"])
            logger.info("Running generator")
            h = await interpret(test, test["generator"], invoke,
                                test["concurrency"],
                                nemesis_invoke=nemesis_invoke)
            await pool.teardown()
            if nemesis_obj is not None:
                await nemesis_obj.teardown(test)
            await db.teardown(test)
            return h

        history = loop.run_coro(main())
        sim_seconds = loop.now / 1e9
    finally:
        set_current_loop(None)

    store_dir = make_store_dir(test.get("store_base", "store"),
                               test.get("name", "test"))
    logger.info("Analyzing %d ops (history in %s)", len(history), store_dir)
    results = test["checker"].check(test, history,
                                    {"store_dir": store_dir})
    node_logs = {name: list(node.etcd_log)
                 for name, node in cluster.nodes.items()}
    save_run(store_dir, test, history, results, node_logs)
    wall = wall_time.time() - t0
    logger.info("Run complete: valid?=%s (%d ops, %.1f sim-s, %.2f wall-s)",
                results.get("valid?"), len(history), sim_seconds, wall)
    return {"valid?": results.get("valid?"), "results": results,
            "history": history, "dir": store_dir,
            "sim-seconds": sim_seconds, "wall-seconds": wall}
