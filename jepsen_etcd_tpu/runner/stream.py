"""Streaming online checking: feed recorded op columns to checker
front-ends while generation is still running.

``StreamFeed`` attaches to the interpreter's ``ColumnsBuilder`` and,
every ``chunk_ops`` recorded events, drains a columnar chunk
(``take_chunk``) onto a worker thread that advances per-workload
incremental consumers — the resumable register pack extractor
(``ops.wgl.PackStream``) and the set scan (``checkers.set_full.
ColumnScan``). When the run ends, ``finish()`` finalizes the consumers
and installs their artifacts as reuse hints on ``test["_stream"]``.

Bit-identity contract: streaming consumers only ever produce REUSE
HINTS — precomputed artifacts the post-hoc checkers validate (row
count against the final history, key coverage) and then consume in
place of their own scan/pack pass. Every decision phase runs the exact
post-hoc code, so verdicts are bit-identical with hints present,
absent, or half-fed; a consumer that trips on a malformed stream
simply withdraws its hint and the checker recomputes from scratch.

Overlap honesty: the sim's generator loop is CPU-bound Python, so
under the GIL a streamed consumer mostly interleaves with generation
instead of running beside it (PERF.md §streaming carries the measured
accounting). The wins are (a) live runs, whose generation is I/O-bound
wall time the consumers genuinely overlap; (b) bounded-memory soak
windows; (c) phase:check collapsing to the vectorized finalize because
the scan/pack artifacts are ready the moment generation ends.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Optional

from . import telemetry

logger = logging.getLogger("jepsen_etcd_tpu.run")

DEFAULT_CHUNK_OPS = 1024

#: join bound for the worker at finish; a wedged consumer must not
#: hang the run — the feed just withdraws its hints past this
JOIN_TIMEOUT_S = 300.0


class StreamFeed:
    """One run's streaming pipeline: chunk pump + consumer worker."""

    def __init__(self, test: dict, chunk_ops: int = DEFAULT_CHUNK_OPS):
        self.test = test
        self.chunk_ops = max(1, int(chunk_ops or DEFAULT_CHUNK_OPS))
        self.columns = None           # the interpreter's ColumnsBuilder
        self._since = 0               # ops recorded since last flush
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self.chunks = 0
        self.rows = 0                 # rows consumed by the worker
        self.backlog_peak = 0
        self.error: Optional[BaseException] = None
        # set under _cv when finish() gives up on a wedged worker; the
        # worker must not install finalize results past this point
        self._abandoned = False
        # per-workload consumers, created lazily on the worker thread
        wl = test.get("workload") if isinstance(test, dict) else None
        self._want_pack = wl == "register"
        self._want_scan = wl == "set"
        self._pack = None             # ops.wgl.PackStream
        self._scan = None             # checkers.set_full.ColumnScan
        self._pack_result = None
        self._scan_result = None

    # -- producer side (interpreter loop) ------------------------------------

    def attach(self, columns: Any) -> None:
        """Bind the interpreter's ColumnsBuilder and start the worker."""
        self.columns = columns
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._worker, name="stream-checker", daemon=True)
            self._thread.start()

    def on_record(self) -> None:
        """Per-op tick from the interpreter's record(); flushes a chunk
        every ``chunk_ops`` events. O(1) between flushes."""
        self._since += 1
        if self._since >= self.chunk_ops:
            self._since = 0
            self._flush()

    def _flush(self) -> None:
        if self.columns is None:
            return
        cols = self.columns.take_chunk()
        if cols is None or len(cols) == 0:
            return
        with self._cv:
            # enqueue stamp feeds the stream.chunk_lag_s histogram
            # (host wall time only — never reaches history/verdict)
            # graftlint: ignore[DET001] telemetry-only host timing
            self._q.append((cols, time.monotonic()))
            if len(self._q) > self.backlog_peak:
                self.backlog_peak = len(self._q)
            self._cv.notify()

    # -- consumer side (worker thread) ---------------------------------------

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait()
                if not self._q and self._closed:
                    break
                cols, t_enq = self._q.popleft()
            # graftlint: ignore[DET001] telemetry-only host timing
            lag = time.monotonic() - t_enq
            telemetry.current().hist("stream.chunk_lag_s", lag)
            try:
                self._consume(cols)
            except BaseException as e:  # withdraw hints, never crash a run
                logger.warning("stream consumer failed; hints withdrawn",
                               exc_info=True)
                with self._cv:
                    self.error = e
                    self._pack = self._scan = None
                    self._want_pack = self._want_scan = False
        try:
            self._finalize_consumers()
        except BaseException as e:
            logger.warning("stream finalize failed; hints withdrawn",
                           exc_info=True)
            with self._cv:
                self.error = e
                self._pack_result = self._scan_result = None

    def _consume(self, cols: Any) -> None:
        tel = telemetry.current()
        with tel.span("stream.chunk", rows=len(cols)):
            if self._want_pack:
                if self._pack is None:
                    from ..ops.wgl import PackStream
                    with self._cv:
                        self._pack = PackStream()
                self._pack.feed(cols)
            if self._want_scan:
                if self._scan is None:
                    from ..checkers.set_full import ColumnScan
                    with self._cv:
                        self._scan = ColumnScan()
                try:
                    self._scan.feed(cols)
                except Exception:  # _NonColumnar rows: scan withdrawn
                    with self._cv:
                        self._scan = None
                        self._want_scan = False
        with self._cv:
            self.chunks += 1
            self.rows += len(cols)
        tel.counter("stream.chunks")
        tel.counter("stream.flushed_events", len(cols))

    def _finalize_consumers(self) -> None:
        tel = telemetry.current()
        pack_result = scan_result = None
        if self._pack is not None:
            with tel.span("stream.finalize", kind="register-pack"):
                pack_result = self._pack.finish()  # None if bad
        if self._scan is not None:
            with tel.span("stream.finalize", kind="set-scan"):
                scan_result = self._scan.finish()
        # a worker that wedged past finish()'s join bound must not
        # install results the run already declared withdrawn
        with self._cv:
            if not self._abandoned:
                self._pack_result = pack_result
                self._scan_result = scan_result

    # -- epilogue (runner, after generation) ---------------------------------

    def finish(self, history: Any) -> dict:
        """Drain the tail, join the worker, validate, and install the
        hint map as ``test["_stream"]``. Returns the hint map."""
        self._flush()
        with self._cv:
            self._closed = True
            self._cv.notify()
        if self._thread is not None:
            self._thread.join(timeout=JOIN_TIMEOUT_S)
            if self._thread.is_alive():
                logger.warning("stream worker did not drain in %.0fs; "
                               "hints withdrawn", JOIN_TIMEOUT_S)
                with self._cv:
                    self._abandoned = True
                    self._pack_result = self._scan_result = None
        tel = telemetry.current()
        # snapshot under the lock: a worker alive past the join bound
        # must not mutate what this epilogue publishes
        with self._cv:
            error = self.error
            chunks, rows = self.chunks, self.rows
            pack_result = self._pack_result
            scan_result = self._scan_result
        tel.counter("stream.backlog_peak", self.backlog_peak, mode="max")
        hints: dict = {"stats": {"chunks": chunks,
                                 "rows": rows,
                                 "backlog_peak": self.backlog_peak,
                                 "chunk_ops": self.chunk_ops}}
        # hints are only safe when the worker consumed the WHOLE
        # recorded stream — a partial feed (error, wedged worker) must
        # not masquerade as the full history's artifacts
        if error is None and rows == len(history):
            if pack_result is not None:
                hints["register_packs"] = (pack_result, rows)
            if scan_result is not None:
                hints["set_scan"] = (scan_result, rows)
        self.test["_stream"] = hints
        return hints
