"""Streaming online checking: feed recorded op columns to checker
front-ends while generation is still running.

``StreamFeed`` attaches to the interpreter's ``ColumnsBuilder`` and,
every ``chunk_ops`` recorded events, drains a columnar chunk
(``take_chunk``) onto a worker thread that advances per-workload
incremental consumers — the resumable register pack extractor
(``ops.wgl.PackStream``) and the set scan (``checkers.set_full.
ColumnScan``). When the run ends, ``finish()`` finalizes the consumers
and installs their artifacts as reuse hints on ``test["_stream"]``.

Bit-identity contract: streaming consumers only ever produce REUSE
HINTS — precomputed artifacts the post-hoc checkers validate (row
count against the final history, key coverage) and then consume in
place of their own scan/pack pass. Every decision phase runs the exact
post-hoc code, so verdicts are bit-identical with hints present,
absent, or half-fed; a consumer that trips on a malformed stream
simply withdraws its hint and the checker recomputes from scratch.

Overlap honesty: the sim's generator loop is CPU-bound Python, so
under the GIL a streamed consumer mostly interleaves with generation
instead of running beside it (PERF.md §streaming carries the measured
accounting). The wins are (a) live runs, whose generation is I/O-bound
wall time the consumers genuinely overlap; (b) bounded-memory soak
windows; (c) phase:check collapsing to the vectorized finalize because
the scan/pack artifacts are ready the moment generation ends.

``FusedPipeline`` is the device-resident leg PERF.md §streaming
deferred: the epoch-v3 jitted generator (simbatch/engine_jax.py)
produces seed sub-batches while a consumer thread packs each finished
history (``PackStream`` fed columnar row slices) and advances
``check_prefix`` frontiers — both legs spend their hot loops inside
jitted device dispatches that release the GIL, so one campaign cell's
wall approaches max(gen, check) instead of gen + check. Soundness
rides the same reuse-hint argument as StreamFeed: the packs and the
chunked ladder are bit-identical to their one-shot forms
(tests/test_stream.py pins both), so the pipeline changes WHEN
checking happens, never what it concludes.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Optional

from . import telemetry

logger = logging.getLogger("jepsen_etcd_tpu.run")

DEFAULT_CHUNK_OPS = 1024

#: join bound for the worker at finish; a wedged consumer must not
#: hang the run — the feed just withdraws its hints past this
JOIN_TIMEOUT_S = 300.0


class StreamFeed:
    """One run's streaming pipeline: chunk pump + consumer worker."""

    def __init__(self, test: dict, chunk_ops: int = DEFAULT_CHUNK_OPS):
        self.test = test
        self.chunk_ops = max(1, int(chunk_ops or DEFAULT_CHUNK_OPS))
        self.columns = None           # the interpreter's ColumnsBuilder
        self._since = 0               # ops recorded since last flush
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self.chunks = 0
        self.rows = 0                 # rows consumed by the worker
        self.backlog_peak = 0
        self.error: Optional[BaseException] = None
        # set under _cv when finish() gives up on a wedged worker; the
        # worker must not install finalize results past this point
        self._abandoned = False
        # per-workload consumers, created lazily on the worker thread
        wl = test.get("workload") if isinstance(test, dict) else None
        self._want_pack = wl == "register"
        self._want_scan = wl == "set"
        self._pack = None             # ops.wgl.PackStream
        self._scan = None             # checkers.set_full.ColumnScan
        self._pack_result = None
        self._scan_result = None

    # -- producer side (interpreter loop) ------------------------------------

    def attach(self, columns: Any) -> None:
        """Bind the interpreter's ColumnsBuilder and start the worker."""
        self.columns = columns
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._worker, name="stream-checker", daemon=True)
            self._thread.start()

    def on_record(self) -> None:
        """Per-op tick from the interpreter's record(); flushes a chunk
        every ``chunk_ops`` events. O(1) between flushes."""
        self._since += 1
        if self._since >= self.chunk_ops:
            self._since = 0
            self._flush()

    def _flush(self) -> None:
        if self.columns is None:
            return
        cols = self.columns.take_chunk()
        if cols is None or len(cols) == 0:
            return
        with self._cv:
            # enqueue stamp feeds the stream.chunk_lag_s histogram
            # (host wall time only — never reaches history/verdict)
            self._q.append((cols, time.monotonic()))
            if len(self._q) > self.backlog_peak:
                self.backlog_peak = len(self._q)
            self._cv.notify()

    # -- consumer side (worker thread) ---------------------------------------

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait()
                if not self._q and self._closed:
                    break
                cols, t_enq = self._q.popleft()
            lag = time.monotonic() - t_enq
            telemetry.current().hist("stream.chunk_lag_s", lag)
            try:
                self._consume(cols)
            except BaseException as e:  # withdraw hints, never crash a run
                logger.warning("stream consumer failed; hints withdrawn",
                               exc_info=True)
                with self._cv:
                    self.error = e
                    self._pack = self._scan = None
                    self._want_pack = self._want_scan = False
        try:
            self._finalize_consumers()
        except BaseException as e:
            logger.warning("stream finalize failed; hints withdrawn",
                           exc_info=True)
            with self._cv:
                self.error = e
                self._pack_result = self._scan_result = None

    def _consume(self, cols: Any) -> None:
        tel = telemetry.current()
        with tel.span("stream.chunk", rows=len(cols)):
            if self._want_pack:
                if self._pack is None:
                    from ..ops.wgl import PackStream
                    with self._cv:
                        self._pack = PackStream()
                self._pack.feed(cols)
            if self._want_scan:
                if self._scan is None:
                    from ..checkers.set_full import ColumnScan
                    with self._cv:
                        self._scan = ColumnScan()
                try:
                    self._scan.feed(cols)
                except Exception:  # _NonColumnar rows: scan withdrawn
                    with self._cv:
                        self._scan = None
                        self._want_scan = False
        with self._cv:
            self.chunks += 1
            self.rows += len(cols)
        tel.counter("stream.chunks")
        tel.counter("stream.flushed_events", len(cols))

    def _finalize_consumers(self) -> None:
        tel = telemetry.current()
        pack_result = scan_result = None
        if self._pack is not None:
            with tel.span("stream.finalize", kind="register-pack"):
                pack_result = self._pack.finish()  # None if bad
        if self._scan is not None:
            with tel.span("stream.finalize", kind="set-scan"):
                scan_result = self._scan.finish()
        # a worker that wedged past finish()'s join bound must not
        # install results the run already declared withdrawn
        with self._cv:
            if not self._abandoned:
                self._pack_result = pack_result
                self._scan_result = scan_result

    # -- epilogue (runner, after generation) ---------------------------------

    def finish(self, history: Any) -> dict:
        """Drain the tail, join the worker, validate, and install the
        hint map as ``test["_stream"]``. Returns the hint map."""
        self._flush()
        with self._cv:
            self._closed = True
            self._cv.notify()
        if self._thread is not None:
            self._thread.join(timeout=JOIN_TIMEOUT_S)
            if self._thread.is_alive():
                logger.warning("stream worker did not drain in %.0fs; "
                               "hints withdrawn", JOIN_TIMEOUT_S)
                with self._cv:
                    self._abandoned = True
                    self._pack_result = self._scan_result = None
        tel = telemetry.current()
        # snapshot under the lock: a worker alive past the join bound
        # must not mutate what this epilogue publishes
        with self._cv:
            error = self.error
            chunks, rows = self.chunks, self.rows
            pack_result = self._pack_result
            scan_result = self._scan_result
        tel.counter("stream.backlog_peak", self.backlog_peak, mode="max")
        hints: dict = {"stats": {"chunks": chunks,
                                 "rows": rows,
                                 "backlog_peak": self.backlog_peak,
                                 "chunk_ops": self.chunk_ops}}
        # hints are only safe when the worker consumed the WHOLE
        # recorded stream — a partial feed (error, wedged worker) must
        # not masquerade as the full history's artifacts
        if error is None and rows == len(history):
            if pack_result is not None:
                hints["register_packs"] = (pack_result, rows)
            if scan_result is not None:
                hints["set_scan"] = (scan_result, rows)
        self.test["_stream"] = hints
        return hints


# ---------------------------------------------------------------------------
# fused gen->check pipeline (device-resident leg)


def _slice_columns(cols: Any, lo: int, hi: int) -> Any:
    """Row slice ``[lo, hi)`` of an OpColumns as a standalone
    OpColumns: typed arrays slice as views, the values list copies its
    window, and the sparse extras/missing dicts re-key to the slice's
    local row numbers. Tables are shared by reference — a slice is a
    chunk of the SAME stream, exactly what ``ColumnsBuilder.
    take_chunk`` hands StreamFeed."""
    from ..core.history import OpColumns

    lo = max(0, int(lo))
    hi = min(len(cols), int(hi))
    return OpColumns(
        cols.type_code[lo:hi], cols.f_code[lo:hi], cols.proc[lo:hi],
        cols.key_id[lo:hi], cols.time[lo:hi], cols.index[lo:hi],
        cols.values[lo:hi],
        {r - lo: v for r, v in cols.extras.items() if lo <= r < hi},
        {r - lo: v for r, v in cols.missing.items() if lo <= r < hi},
        cols.f_table, cols.key_table, cols.proc_table)


class FusedPipeline:
    """One campaign cell's gen->check overlap: the epoch-v3 jitted
    generator produces seed sub-batches while a consumer thread packs
    each finished history and advances ``check_prefix`` frontiers.

    The producer (caller thread) runs ``generate_jax`` per sub-batch
    and enqueues finished histories; the consumer drains them — each
    history chunk-feeds a fresh ``PackStream`` (columnar row slices,
    the adversarial-boundary invariant tests/test_stream.py pins),
    ``finish()`` yields the per-key packs, and every pack's frontier
    advances through the chunked ladder until done. Both hot loops
    live inside jitted dispatches that release the GIL, so e2e wall
    approaches max(gen, check) — the ``fused_pipeline`` bench cell
    reports the measured ratio against the sequential leg.

    Verdict soundness is inherited, not re-argued: packs and the
    chunked ladder are bit-identical to their one-shot forms, so a
    fused cell's verdicts match the sequential cell's exactly (the
    bench cell asserts this on every run)."""

    def __init__(self, opts: dict, sub_batch: int = 4,
                 chunk_rows: int = DEFAULT_CHUNK_OPS,
                 max_waves: int = 256):
        from ..simbatch import BatchConfig

        if opts.get("workload", "register") != "register":
            raise ValueError("FusedPipeline checks register packs; "
                             f"workload {opts.get('workload')!r} has no "
                             "packable per-key decomposition")
        self.opts = dict(opts, gen_epoch="epoch-v3")
        self.config = BatchConfig.from_opts(self.opts)
        self.sub_batch = max(1, int(sub_batch))
        self.chunk_rows = max(1, int(chunk_rows))
        self.max_waves = max(1, int(max_waves))
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._done_producing = False
        self.gen_s = 0.0              # producer busy wall
        self.check_s = 0.0            # consumer busy wall
        self.e2e_s = 0.0
        self.verdicts: list = []      # (seed, bool) in completion order
        self.packs = 0
        self.waves = 0
        self.error: Optional[BaseException] = None

    # -- consumer leg ---------------------------------------------------------

    def _check_history(self, seed: int, history: Any) -> tuple:
        """One history's pack + frontier walk: ``(verdict, packs,
        waves)``. Pure accounting return — shared state stays with the
        caller, so the consumer thread can batch its publication."""
        from ..ops.wgl import PackStream, check_prefix

        cols = history.columns
        ps = PackStream()
        for lo in range(0, len(cols), self.chunk_rows):
            ps.feed(_slice_columns(cols, lo, lo + self.chunk_rows))
        packs = ps.finish()
        if packs is None:
            return "unknown", 0, 0
        vs = []
        waves = 0
        for p in packs.values():
            state = check_prefix(p, None, max_waves=self.max_waves)
            while not state.done:
                state = check_prefix(p, state,
                                     max_waves=self.max_waves)
            waves += state.waves_run
            vs.append(state.result.get("valid?"))
        # False dominates (a real violation), then unknown, then True
        verdict = (False if any(v is False for v in vs)
                   else "unknown" if any(v is not True for v in vs)
                   else True)
        return verdict, len(packs), waves

    def _consumer(self) -> None:
        tel = telemetry.current()
        verdicts: list = []
        packs = waves = 0
        busy = 0.0
        try:
            while True:
                with self._cv:
                    while not self._q and not self._done_producing:
                        self._cv.wait()
                    if not self._q and self._done_producing:
                        return
                    seed, history = self._q.popleft()
                t0 = time.monotonic()
                try:
                    with tel.span("fused.check", seed=seed):
                        v, n_packs, n_waves = self._check_history(
                            seed, history)
                    verdicts.append((seed, v))
                    packs += n_packs
                    waves += n_waves
                except BaseException as e:
                    with self._cv:
                        self.error = e
                    logger.warning("fused consumer failed",
                                   exc_info=True)
                    return
                finally:
                    busy += time.monotonic() - t0
        finally:
            # publish the thread-local accounting once, under the lock
            # (run() only reads after join, but the lock keeps the
            # cross-thread hand-off explicit)
            with self._cv:
                self.verdicts.extend(verdicts)
                self.packs += packs
                self.waves += waves
                self.check_s += busy

    # -- producer leg (caller thread) -----------------------------------------

    def run(self, seeds) -> dict:
        """Generate + check every seed, overlapped; returns the timing
        summary the ``fused_pipeline`` bench cell reports."""
        from ..simbatch.engine_jax import generate_jax

        seeds = [int(s) for s in seeds]
        tel = telemetry.current()
        worker = threading.Thread(target=self._consumer,
                                  name="fused-checker", daemon=True)
        t_start = time.monotonic()
        worker.start()
        for i in range(0, len(seeds), self.sub_batch):
            sub = seeds[i:i + self.sub_batch]
            t0 = time.monotonic()
            with tel.span("fused.gen", seeds=len(sub)):
                out = generate_jax(self.config, sub)
            self.gen_s += time.monotonic() - t0
            with self._cv:
                for sd, h in zip(sub, out["histories"]):
                    self._q.append((sd, h))
                self._cv.notify()
        with self._cv:
            self._done_producing = True
            self._cv.notify()
        worker.join()
        self.e2e_s = time.monotonic() - t_start
        if self.error is not None:
            raise self.error
        tel.counter("fused.seeds", len(seeds))
        tel.counter("fused.packs", self.packs)
        tel.counter("fused.waves", self.waves)
        floor = max(self.gen_s, self.check_s) or 1e-9
        return {"seeds": len(seeds), "gen_s": self.gen_s,
                "check_s": self.check_s, "e2e_s": self.e2e_s,
                "ratio": self.e2e_s / floor,
                "packs": self.packs, "waves": self.waves,
                "verdicts": dict(self.verdicts)}
