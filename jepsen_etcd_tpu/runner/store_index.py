"""The indexed artifact store: a sqlite run index under each store base.

The reference harness re-reads every run's artifacts wholesale on each
``/aggregate`` request and each ``tel`` invocation — O(all runs ever)
per query, fatal at fleet scale (ROADMAP direction 5). This module
keeps one append-only index per store base (``<base>/index.sqlite``,
WAL mode): one row per run/campaign/guided/shrink artifact holding the
EXACT summary dict the dashboards consume, written at
``save_run``/campaign-fold time and replayed incrementally by readers
through a per-process high-water-mark fold.

Layout facts the index encodes (runner/campaign.py:595): every run
lands exactly TWO levels below its store base, so a run's index lives
at ``dirname(dirname(run_dir))/index.sqlite``. Guided campaigns pass
``store_base=<guided dir>``, which makes each guided dir its own index
base; readers that need the full tree (``tel --coverage``, the shrink
table) recurse through the base index's guided rows into those
sub-indexes.

Row derivation is shared with the tree-walk paths (serve.py and
tel_cli.py call :func:`run_row` / :func:`coverage_fields` / … on both
sides), so index-backed output is bit-identical to a walk by
construction — the property tests/test_store_index.py pins.

Change feed: every insert/update/tombstone bumps a monotonically
increasing ``seq`` inside the write transaction; a reader folds
``seq > hwm`` only. A full ``rebuild`` bumps the ``epoch`` meta key so
stale folds drop their cache instead of merging across generations.

No jax, no wall clock, no randomness — safe to import anywhere.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import sqlite3
from typing import Any, Optional

from .store import failure_signature
from .telemetry import Hist, load_jsonl

INDEX_NAME = "index.sqlite"
SCHEMA_VERSION = 1

#: artifacts `store compact` keeps in a demoted passing run — the
#: summaries every reader consumes. Everything else in the run dir
#: (history.jsonl, telemetry.jsonl, trace.jsonl, plots, node log
#: dirs) is deleted; FAILING runs are never touched at all.
COMPACT_KEEP = ("results.json", "test.json", "shrink.json")

#: newest runs `store compact` always spares, regardless of verdict
COMPACT_KEEP_NEWEST = 32

_DDL = """
CREATE TABLE IF NOT EXISTS rows (
    kind TEXT NOT NULL,
    dir TEXT NOT NULL,
    seq INTEGER NOT NULL,
    mtime REAL,
    deleted INTEGER NOT NULL DEFAULT 0,
    compacted INTEGER NOT NULL DEFAULT 0,
    row TEXT NOT NULL,
    PRIMARY KEY (kind, dir));
CREATE INDEX IF NOT EXISTS rows_seq ON rows (seq);
CREATE TABLE IF NOT EXISTS tel_cache (
    path TEXT PRIMARY KEY,
    mtime_ns INTEGER,
    size INTEGER,
    profile TEXT);
CREATE TABLE IF NOT EXISTS meta (
    key TEXT PRIMARY KEY,
    value TEXT);
"""


# -- row derivation (shared by index writers AND tree-walk readers) ----------

def overlap_ratio(phases: dict, counters: dict):
    """End-to-end-over-generation ratio for streamed runs: how close
    checking came to free. (generate + stream-finalize + check) /
    generate — 1.0 means verification added no wall time beyond
    generation. None for runs that never streamed a chunk."""
    if not counters.get("stream.chunks"):
        return None
    gen = phases.get("generate")
    if not isinstance(gen, (int, float)) or gen <= 0:
        return None
    extra = sum(phases.get(k) or 0 for k in ("stream-finalize", "check"))
    return (gen + extra) / gen


#: MVCC consistency-surface checker keys (checkers/mvcc.py) surfaced
#: as their own /aggregate column: surface name -> short label
SURFACES = {"staleness": "stale", "ranges": "ranges",
            "lease": "lease", "watch-mvcc": "watch"}


def consistency_surface(results: dict) -> dict:
    """``{label: {"valid": verdict, "violations": n}}`` for every MVCC
    surface checker that ran in this run's composed workload result."""
    wlr = results.get("workload")
    out = {}
    if isinstance(wlr, dict):
        for key, label in SURFACES.items():
            sub = wlr.get(key)
            if isinstance(sub, dict) and "valid?" in sub:
                out[label] = {
                    "valid": sub.get("valid?"),
                    "violations": sub.get("violation-count", 0)}
    return out


def run_row(rel: str, results: dict, test: dict, mtime: float) -> dict:
    """The /aggregate run row for one saved run — the single source
    serve.py's walk path and the index writer both call, so stored
    rows replay bit-identically."""
    ops = (results.get("stats") or {}).get("count")
    tel = results.get("telemetry") or {}
    nem = test.get("nemesis_spec") or []
    if isinstance(nem, (list, tuple)):
        nem = ",".join(str(n) for n in nem)
    return {"dir": rel, "mtime": mtime,
            "valid?": results.get("valid?", "?"),
            "name": test.get("name", rel.split(os.sep)[0]),
            "workload": test.get("workload", "?"),
            "nemesis": nem or "none",
            "db": test.get("db_mode") or "sim",
            "time_limit": test.get("time_limit"),
            "ops": ops,
            "phases": tel.get("phases") or {},
            "gen_rate": (tel.get("counters") or {})
            .get("generate.ops_per_s"),
            "overlap": overlap_ratio(
                tel.get("phases") or {},
                tel.get("counters") or {}),
            "consistency": consistency_surface(results),
            "signature": failure_signature(results)}


def host_ledger(summary: dict, sctr: dict) -> Optional[dict]:
    """Per-host attribution for a multi-host campaign: the rows' fold
    (runs + shipped per host, producer side) joined with the service's
    ``service.host_submitted.<host>`` counters (consumer side). The
    two shipped numbers must agree — that is the cross-host
    shipped==submitted ledger. None for single-host campaigns."""
    hosts = summary.get("hosts")
    if not isinstance(hosts, dict) or not hosts:
        return None
    out = {}
    for h, st in sorted(hosts.items()):
        st = dict(st) if isinstance(st, dict) else {}
        st["submitted"] = sctr.get("service.host_submitted." + h)
        out[h] = st
    return out


def chip_util(sctr: dict) -> Optional[dict]:
    """Per-chip utilization summary from a campaign's folded service
    counters (the sharded dispatcher's ledger): group dispatches and
    busy wall per device, the max/min dispatch balance ratio, and peak
    per-tick device occupancy. None for single-device/legacy
    campaigns, which recorded no per-device dispatch series."""
    pfx_d = "service.device_dispatches."
    pfx_b = "service.device_busy_s."
    disp = {k[len(pfx_d):]: int(v or 0) for k, v in sctr.items()
            if k.startswith(pfx_d)}
    if not disp:
        return None
    busy = {k[len(pfx_b):]: float(v or 0.0) for k, v in sctr.items()
            if k.startswith(pfx_b)}
    lo = min(disp.values())
    return {
        "devices": len(disp),
        "dispatches": disp,
        "busy_s": busy,
        "balance": (max(disp.values()) / lo) if lo else None,
        "occupancy": sctr.get("service.device_occupancy"),
        "sharded_ticks": sctr.get("service.sharded_ticks"),
    }


def campaign_row(rel: str, summary: dict, mtime: float) -> dict:
    """The /aggregate campaign-trend row for one campaign.json."""
    runs = [r for r in (summary.get("runs") or [])
            if isinstance(r, dict)]
    done = [r for r in runs if r.get("status") == "done"]
    rates = [r["gen_ops_per_s"] for r in done
             if isinstance(r.get("gen_ops_per_s"), (int, float))]
    sctr = ((summary.get("service") or {}).get("counters") or {})
    svc_disp = sum(int(sctr.get(k, 0) or 0)
                   for k in ("wgl.dispatches", "mxu.dispatches"))
    local_disp = sum(int(r.get("dispatches") or 0) for r in done)
    # lossy-link diagnosis triple, summed over the rows' net.*
    # counters (runner/campaign._row_net)
    net = {"dropped_chunks": 0, "accept_errors": 0, "delayed_bytes": 0}
    for r in done:
        for k in net:
            try:
                net[k] += int((r.get("net") or {}).get(k) or 0)
            except (TypeError, ValueError):
                pass
    return {
        "dir": rel,
        "mtime": mtime, "name": summary.get("name",
                                            rel.split(os.sep)[0]),
        "count": summary.get("count"),
        "pool": summary.get("pool"),
        "valid?": summary.get("valid?", "?"),
        "wall_s": summary.get("wall_s"),
        "gen_rate": (sum(rates) / len(rates)) if rates else None,
        # batched lockstep generation (simbatch epoch-v2 routing):
        # aggregate events/s across each cell's seed batch, None for
        # epoch-v1-only campaigns
        "genbatch": summary.get("genbatch") or None,
        "check_s": sum(r.get("check_s") or 0 for r in done),
        "dispatches": svc_disp + local_disp,
        "submitted": sctr.get("service.submitted"),
        "group_ticks": sctr.get("service.group_ticks"),
        "occupancy": sctr.get("service.batch_occupancy"),
        "chips": chip_util(sctr),
        "fallbacks": sum(int(r.get("service_fallbacks") or 0)
                         for r in done),
        # multi-host campaigns: per-host run/shipped fold joined
        # against the service's per-host submitted series (the
        # cross-host ledger, runner/host_agent.py)
        "hosts": host_ledger(summary, sctr),
        "agent_requeues": int(summary.get("agent_requeues") or 0),
        # campaign-wide merged-histogram percentiles
        # ({label: [p50, p95, p99]}, seconds)
        "p": summary.get("p") if isinstance(summary.get("p"), dict)
        else {},
        "net": net,
    }


def guided_row(rel: str, summary: dict, mtime: float) -> dict:
    """The /aggregate guided-campaign row for one guided.json."""
    return {
        "dir": rel,
        "mtime": mtime,
        "name": summary.get("name", rel.split(os.sep)[0]),
        "budget": summary.get("budget"),
        "runs": summary.get("runs"),
        "generations": summary.get("generations"),
        "signatures": summary.get("signatures") or {},
        "first_failure_run": summary.get("first_failure_run"),
        "corpus": len(summary.get("corpus") or []),
        "minimized": summary.get("minimized") or [],
        "wall_s": summary.get("wall_s"),
    }


def shrink_row(rel: str, art: dict, mtime: float) -> dict:
    """The /aggregate minimized-repro row for one shrink.json."""
    return {
        "dir": rel,
        "mtime": mtime,
        "workload": art.get("workload"),
        "signature": art.get("signature"),
        "original_windows": art.get("original_windows"),
        "windows": art.get("windows"),
        "nemesis_ops": art.get("nemesis_ops"),
        "rounds": art.get("rounds"),
        "executions": art.get("executions"),
        "repro": art.get("repro"),
    }


def coverage_fields(results: Any) -> Optional[dict]:
    """The ``tel --coverage`` feature vector of one run (minus its
    ``dir``): checker effort (frontier/rungs/spills/wave depth), the
    per-rung dispatch-shape histogram, and the verdict signature.
    None for unreadable/non-dict results (the walk skips those)."""
    if not isinstance(results, dict):
        return None
    tel_sum = results.get("telemetry") or {}
    ctr = tel_sum.get("counters") or {}
    # per-rung dispatch shape: the wgl.rung_waves histogram puts each
    # ladder rung in its own log2 bucket, so {bucket: dispatches} IS
    # the search-depth distribution — guided novelty scores
    # newly-occupied buckets (+1 each)
    wave_hist = {
        int(b): int(c)
        for b, c in (((tel_sum.get("hists") or {})
                      .get("wgl.rung_waves") or {})
                     .get("buckets") or {}).items()}
    return {"valid": results.get("valid?"),
            "frontier": int(ctr.get("wgl.max-frontier", 0)),
            "rungs": int(ctr.get("wgl.rungs", 0)),
            "spills": int(ctr.get("wgl.host-spill", 0)),
            "waves": int(ctr.get("wgl.waves", 0)),
            "wave_hist": wave_hist,
            "signature": failure_signature(results)}


def _cov_restore(cov: dict) -> dict:
    """A coverage vector back from its JSON index row: wave_hist keys
    are ints in the live vector but strings after a JSON round-trip —
    ``json.dumps(sort_keys=True)`` orders int keys numerically and str
    keys lexically ("10" < "3"), so the restore is load-bearing for
    bit-identical ``tel --coverage`` output."""
    out = dict(cov)
    out["wave_hist"] = {int(b): int(c)
                        for b, c in (cov.get("wave_hist") or {}).items()}
    return out


def _load_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None


# -- sqlite plumbing ----------------------------------------------------------

def _db_path(base: str) -> str:
    return os.path.join(base, INDEX_NAME)


def has_index(base: str) -> bool:
    return os.path.isfile(_db_path(base))


def _connect(base: str, create: bool = False):
    """A WAL-mode connection to the base's index, or None when the
    index does not exist and ``create`` is False."""
    path = _db_path(base)
    if not create and not os.path.isfile(path):
        return None
    if create and not os.path.isdir(base):
        os.makedirs(base, exist_ok=True)
    con = sqlite3.connect(path, timeout=30.0)
    con.isolation_level = None  # explicit BEGIN/COMMIT only
    con.execute("PRAGMA journal_mode=WAL")
    con.execute("PRAGMA synchronous=NORMAL")
    con.executescript(_DDL)
    con.execute(
        "INSERT OR IGNORE INTO meta (key, value) VALUES ('schema', ?)",
        (str(SCHEMA_VERSION),))
    return con


def _counter(name: str, value: float = 1) -> None:
    from . import telemetry
    telemetry.current().counter(name, value)


def _next_seq(con) -> int:
    return int(con.execute(
        "SELECT COALESCE(MAX(seq), 0) FROM rows").fetchone()[0]) + 1


def _upsert(con, entries) -> int:
    """Write (kind, rel, mtime, deleted, compacted, row_dict) tuples
    under one already-open transaction, each with a fresh seq."""
    seq = _next_seq(con) - 1
    n = 0
    for kind, rel, mtime, deleted, compacted, row in entries:
        seq += 1
        con.execute(
            "INSERT INTO rows (kind, dir, seq, mtime, deleted, "
            "compacted, row) VALUES (?, ?, ?, ?, ?, ?, ?) "
            "ON CONFLICT (kind, dir) DO UPDATE SET "
            "seq=excluded.seq, mtime=excluded.mtime, "
            "deleted=excluded.deleted, compacted=excluded.compacted, "
            "row=excluded.row",
            (kind, rel, seq, mtime, int(bool(deleted)),
             int(bool(compacted)), json.dumps(row or {},
                                              sort_keys=True)))
        n += 1
    return n


def _write(base: str, entries, create: bool = True) -> int:
    """Transactionally upsert entries into the base's index;
    best-effort (a failed index write must never fail a run save).
    Returns the number of rows written (0 on any failure).

    First write into an UNINDEXED base triggers a full rebuild
    instead: upserting one row into a fresh index over a pre-existing
    tree would leave readers trusting a partial index. The rebuild
    already covers artifacts on disk; the upsert after it is an
    idempotent no-op for those and still lands not-yet-on-disk rows
    (note_live registrations)."""
    try:
        if create and not has_index(base):
            rebuild(base)
    except (sqlite3.Error, OSError):
        return 0
    try:
        con = _connect(base, create=create)
    except (sqlite3.Error, OSError):
        return 0
    if con is None:
        return 0
    try:
        con.execute("BEGIN IMMEDIATE")
        n = _upsert(con, entries)
        con.execute("COMMIT")
        if n:
            _counter("store.index_writes", n)
        return n
    except (sqlite3.Error, OSError):
        try:
            con.execute("ROLLBACK")
        except sqlite3.Error:
            pass
        return 0
    finally:
        con.close()


def mark_deleted(base: str, rels) -> None:
    """Tombstone every kind of row at the given relative dirs (store
    rotation removed them from disk)."""
    try:
        con = _connect(base, create=False)
    except (sqlite3.Error, OSError):
        return
    if con is None:
        return
    try:
        con.execute("BEGIN IMMEDIATE")
        seq = _next_seq(con)
        for rel in sorted(rels):
            con.execute(
                "UPDATE rows SET deleted=1, seq=? "
                "WHERE dir=? AND deleted=0", (seq, rel))
            seq += 1
        con.execute("COMMIT")
    except (sqlite3.Error, OSError):
        try:
            con.execute("ROLLBACK")
        except sqlite3.Error:
            pass
    finally:
        con.close()


# -- index writers (the save_run / fold-time hooks) ---------------------------

def _run_entry(base: str, rel: str):
    rdir = os.path.join(base, rel)
    results = _load_json(os.path.join(rdir, "results.json"))
    test = _load_json(os.path.join(rdir, "test.json"))
    try:
        mtime = os.path.getmtime(rdir)
    except OSError:
        mtime = 0
    serve = run_row(rel, results if isinstance(results, dict) else {},
                    test if isinstance(test, dict) else {}, mtime)
    compacted = not os.path.exists(os.path.join(rdir, "history.jsonl"))
    row = {"serve": serve, "cov": coverage_fields(results)}
    return ("run", rel, mtime, 0, compacted, row)


def record_run(store_dir: str) -> bool:
    """Index one saved run (called by store.save_run after the
    artifacts hit disk). The row is derived by re-reading the exact
    JSON just written, so it replays bit-identically to a tree walk."""
    store_dir = os.path.abspath(store_dir)
    base = os.path.dirname(os.path.dirname(store_dir))
    rel = os.path.relpath(store_dir, base)
    return _write(base, [_run_entry(base, rel)]) > 0


def _ledger_payload(cdir: str) -> dict:
    """The ``tel --ledger`` trace-join inputs, captured at campaign
    fold time (service.jsonl is complete then): every trace id named
    by a service.tick span, plus the torn-line count — with the file's
    fingerprint so readers can detect a post-fold rewrite."""
    svc = os.path.join(cdir, "service.jsonl")
    if not os.path.isfile(svc):
        return {"has_service": False}
    recs, skipped = load_jsonl(svc)
    ticked = set()
    for rec in recs:
        if rec.get("kind") == "span" and \
                rec.get("name") == "service.tick":
            ticked.update((rec.get("attrs") or {}).get("runs") or ())
    try:
        st = os.stat(svc)
        fp = [st.st_mtime_ns, st.st_size]
    except OSError:
        fp = None
    return {"has_service": True,
            "ticked": sorted(str(t) for t in ticked),
            "skipped": skipped, "fp": fp}


def _campaign_entry(base: str, rel: str):
    cdir = os.path.join(base, rel)
    cpath = os.path.join(cdir, "campaign.json")
    summary = _load_json(cpath)
    if not isinstance(summary, dict) or "runs" not in summary:
        return None
    try:
        mtime = os.path.getmtime(cpath)
    except OSError:
        mtime = 0
    row = {"serve": campaign_row(rel, summary, mtime),
           "ledger": _ledger_payload(cdir)}
    return ("campaign", rel, mtime, 0, 0, row)


def record_campaign(cdir: str) -> bool:
    """Index one folded campaign (called by run_campaign right after
    campaign.json lands). Also tombstones the dir's 'live' row — the
    campaign row takes over as the live-polling candidate."""
    cdir = os.path.abspath(cdir)
    base = os.path.dirname(os.path.dirname(cdir))
    rel = os.path.relpath(cdir, base)
    entry = _campaign_entry(base, rel)
    if entry is None:
        return False
    return _write(base, [entry,
                         ("live", rel, entry[2], 1, 0, None)]) > 0


def _guided_entry(base: str, rel: str):
    gpath = os.path.join(base, rel, "guided.json")
    summary = _load_json(gpath)
    if not isinstance(summary, dict) or summary.get("kind") != "guided":
        return None
    try:
        mtime = os.path.getmtime(gpath)
    except OSError:
        mtime = 0
    return ("guided", rel, mtime, 0, 0,
            {"serve": guided_row(rel, summary, mtime)})


def record_guided(gdir: str) -> bool:
    """Index one folded guided campaign (guided.json just written)."""
    gdir = os.path.abspath(gdir)
    base = os.path.dirname(os.path.dirname(gdir))
    rel = os.path.relpath(gdir, base)
    entry = _guided_entry(base, rel)
    if entry is None:
        return False
    return _write(base, [entry]) > 0


def _shrink_entry(base: str, rel: str):
    spath = os.path.join(base, rel, "shrink.json")
    art = _load_json(spath)
    if not isinstance(art, dict) or "signature" not in art:
        return None
    try:
        mtime = os.path.getmtime(spath)
    except OSError:
        mtime = 0
    return ("shrink", rel, mtime, 0, 0,
            {"serve": shrink_row(rel, art, mtime)})


def record_shrink(rdir: str) -> bool:
    """Index one shrink.json artifact (written into a run dir)."""
    rdir = os.path.abspath(rdir)
    base = os.path.dirname(os.path.dirname(rdir))
    rel = os.path.relpath(rdir, base)
    entry = _shrink_entry(base, rel)
    if entry is None:
        return False
    return _write(base, [entry]) > 0


def note_live(cdir: str) -> bool:
    """Register a campaign dir as a live-polling candidate the moment
    its LiveCollector starts — serve's SSE tick then stats exactly the
    registered candidates instead of listdir-ing the whole store."""
    cdir = os.path.abspath(cdir)
    base = os.path.dirname(os.path.dirname(cdir))
    rel = os.path.relpath(cdir, base)
    try:
        mtime = os.path.getmtime(cdir)
    except OSError:
        mtime = 0
    return _write(base, [("live", rel, mtime, 0, 0,
                          {"dir": rel})]) > 0


# -- rebuild / verify ---------------------------------------------------------

def _tree_entries(base: str):
    """(entries, guided_rels, stats) from a full two-level scan of the
    base — the backfill inventory for rebuild()."""
    entries = []
    guided_rels = []
    stats = {"runs": 0, "campaigns": 0, "guided": 0, "shrink": 0,
             "live": 0}
    try:
        names = sorted(os.listdir(base))
    except OSError:
        return entries, guided_rels, stats
    for name in names:
        ndir = os.path.join(base, name)
        if name == INDEX_NAME or os.path.islink(ndir) \
                or not os.path.isdir(ndir) or name == "latest":
            continue
        try:
            ids = sorted(os.listdir(ndir))
        except OSError:
            continue
        for rid in ids:
            rdir = os.path.join(ndir, rid)
            if rid == "latest" or os.path.islink(rdir) \
                    or not os.path.isdir(rdir):
                continue
            rel = os.path.join(name, rid)
            if os.path.exists(os.path.join(rdir, "history.jsonl")) or \
                    os.path.exists(os.path.join(rdir, "results.json")):
                entries.append(_run_entry(base, rel))
                stats["runs"] += 1
            if os.path.isfile(os.path.join(rdir, "campaign.json")):
                e = _campaign_entry(base, rel)
                if e is not None:
                    entries.append(e)
                    stats["campaigns"] += 1
            if os.path.isfile(os.path.join(rdir, "guided.json")):
                e = _guided_entry(base, rel)
                if e is not None:
                    entries.append(e)
                    guided_rels.append(rel)
                    stats["guided"] += 1
            if os.path.isfile(os.path.join(rdir, "shrink.json")):
                e = _shrink_entry(base, rel)
                if e is not None:
                    entries.append(e)
                    stats["shrink"] += 1
            if os.path.isfile(os.path.join(rdir, "live.json")) and \
                    not os.path.isfile(os.path.join(rdir,
                                                    "campaign.json")):
                try:
                    mtime = os.path.getmtime(rdir)
                except OSError:
                    mtime = 0
                entries.append(("live", rel, mtime, 0, 0, {"dir": rel}))
                stats["live"] += 1
    return entries, guided_rels, stats


def rebuild(base: str, recurse: bool = True) -> dict:
    """One-shot backfill: re-derive every index row from the tree in a
    single transaction, bumping the fold epoch so cached readers drop
    stale state. Recurses into guided sub-bases by default (their runs
    nest one level deeper than this base's two-level layout)."""
    entries, guided_rels, stats = _tree_entries(base)
    con = _connect(base, create=True)
    try:
        con.execute("BEGIN IMMEDIATE")
        seq0 = _next_seq(con) - 1
        con.execute("DELETE FROM rows")
        # re-insert above the old high-water mark under a new epoch:
        # an old fold must restart, never merge across a rebuild
        seq = seq0
        for kind, rel, mtime, deleted, compacted, row in entries:
            seq += 1
            con.execute(
                "INSERT INTO rows (kind, dir, seq, mtime, deleted, "
                "compacted, row) VALUES (?, ?, ?, ?, ?, ?, ?)",
                (kind, rel, seq, mtime, int(bool(deleted)),
                 int(bool(compacted)),
                 json.dumps(row or {}, sort_keys=True)))
        cur = con.execute("SELECT value FROM meta WHERE key='epoch'")
        got = cur.fetchone()
        epoch = (int(got[0]) if got else 0) + 1
        con.execute("INSERT OR REPLACE INTO meta (key, value) "
                    "VALUES ('epoch', ?)", (str(epoch),))
        con.execute("COMMIT")
    except (sqlite3.Error, OSError):
        try:
            con.execute("ROLLBACK")
        except sqlite3.Error:
            pass
        raise
    finally:
        con.close()
    _counter("store.index_rows", len(entries))
    out = {"ok": True, "base": base, "rows": len(entries), **stats}
    if recurse:
        subs = {}
        for rel in guided_rels:
            subs[rel] = rebuild(os.path.join(base, rel), recurse=False)
        if subs:
            out["sub_indexes"] = subs
    return out


def _fingerprint(rels) -> str:
    return hashlib.sha256(
        "\n".join(sorted(rels)).encode()).hexdigest()[:16]


def verify(base: str) -> dict:
    """The row-count/fingerprint consistency check against the tree:
    the index's live (non-deleted, non-compacted) run rows must name
    exactly the run dirs a fresh walk finds. Compacted rows are
    expected to be absent from the walk — their history.jsonl is gone
    by design."""
    from ..forensics import all_runs
    if not has_index(base):
        return {"ok": False, "base": base,
                "error": f"no {INDEX_NAME} under {base!r} "
                         "(run `store index --rebuild`)"}
    tree = {os.path.relpath(r, base) for r in all_runs(base)}
    f = fold(base)
    live_rows = sorted(d for (k, d), v in f.rows.items()
                       if k == "run" and not v["compacted"])
    compacted = sum(1 for (k, _d), v in f.rows.items()
                    if k == "run" and v["compacted"])
    missing = sorted(tree - set(live_rows))
    stale = sorted(set(live_rows) - tree)
    return {"ok": not missing and not stale, "base": base,
            "tree_runs": len(tree), "index_runs": len(live_rows),
            "compacted": compacted,
            "campaigns": sum(1 for (k, _d) in f.rows if k == "campaign"),
            "guided": sum(1 for (k, _d) in f.rows if k == "guided"),
            "shrink": sum(1 for (k, _d) in f.rows if k == "shrink"),
            "missing": missing, "stale": stale,
            "fingerprint": {"tree": _fingerprint(tree),
                            "index": _fingerprint(live_rows)}}


# -- incremental fold (the reader side) ---------------------------------------

class Fold:
    """Per-process incremental view of one index: the current row set
    plus the seq high-water mark and a generation counter bumped on
    every observed change (render caches key off ``gen``)."""

    __slots__ = ("base", "sig", "hwm", "epoch", "gen", "rows", "kinds")

    def __init__(self, base: str):
        self.base = base
        self.sig = None
        self.hwm = 0
        self.epoch = 0
        self.gen = 0
        #: (kind, rel) -> {"mtime", "compacted", "row"}
        self.rows: dict = {}
        #: kind -> set of live rels, kept in step with ``rows`` so
        #: per-kind reads (the warm /aggregate cache check, the SSE
        #: live scan) cost O(kind count), never O(all rows)
        self.kinds: dict = {}


_FOLDS: dict = {}


def _index_sig(base: str):
    """Cheap change detector: (mtime_ns, size) of the db and its WAL.
    Any committed write touches at least one of the two."""
    out = []
    for suffix in ("", "-wal"):
        try:
            st = os.stat(_db_path(base) + suffix)
            out.append((st.st_mtime_ns, st.st_size))
        except OSError:
            out.append(None)
    return tuple(out)


def fold(base: str) -> Optional[Fold]:
    """The incremental fold of the base's index, or None when no index
    exists (callers fall back to the tree walk). Warm calls cost two
    stats; a changed index replays only rows with ``seq > hwm``."""
    if not has_index(base):
        _FOLDS.pop(os.path.abspath(base), None)
        return None
    key = os.path.abspath(base)
    f = _FOLDS.get(key)
    sig = _index_sig(base)
    if f is not None and f.sig == sig:
        return f
    if f is None:
        f = Fold(key)
        _FOLDS[key] = f
    try:
        con = _connect(base, create=False)
    except (sqlite3.Error, OSError):
        return None
    if con is None:
        return None
    try:
        cur = con.execute("SELECT value FROM meta WHERE key='epoch'")
        got = cur.fetchone()
        epoch = int(got[0]) if got else 0
        if epoch != f.epoch:
            # a rebuild replaced the row set wholesale: restart
            f.rows.clear()
            f.kinds.clear()
            f.hwm = 0
            f.epoch = epoch
            f.gen += 1
        changed = 0
        cur = con.execute(
            "SELECT kind, dir, seq, mtime, deleted, compacted, row "
            "FROM rows WHERE seq > ? ORDER BY seq", (f.hwm,))
        for kind, rel, seq, mtime, deleted, compacted, rowtxt in cur:
            if seq > f.hwm:
                f.hwm = seq
            if deleted:
                f.rows.pop((kind, rel), None)
                f.kinds.get(kind, set()).discard(rel)
            else:
                try:
                    row = json.loads(rowtxt)
                except ValueError:
                    continue
                f.rows[(kind, rel)] = {"mtime": mtime,
                                       "compacted": bool(compacted),
                                       "row": row}
                f.kinds.setdefault(kind, set()).add(rel)
            changed += 1
        if changed:
            f.gen += 1
        f.sig = sig
    except (sqlite3.Error, OSError):
        return None
    finally:
        con.close()
    return f


def kind_dirs(f: Fold, kind: str) -> list:
    """Sorted live rels of one kind — O(kind count) via the registry,
    never a scan of the full row set."""
    return sorted(f.kinds.get(kind, ()))


def _kind_rows(f: Fold, kind: str):
    out = [(d, f.rows[(kind, d)]) for d in f.kinds.get(kind, ())]
    # presort by path components: lexicographic dir-string order and
    # the walks' sorted-listdir order disagree around os.sep ("a-x" <
    # "a/b" as strings, but test dir "a" lists first) — component
    # sorting reproduces the walk exactly, and makes the mtime sorts
    # below deterministic on ties
    out.sort(key=lambda t: t[0].split(os.sep))
    return out


def serve_run_rows(f: Fold) -> list:
    """The /aggregate run rows from the fold, ordered exactly like
    serve's walk path (newest first, walk order on mtime ties)."""
    rows = [dict(v["row"]["serve"]) for _d, v in _kind_rows(f, "run")]
    rows.sort(key=lambda r: r["mtime"], reverse=True)
    return rows


def serve_campaign_rows(f: Fold) -> list:
    rows = [dict(v["row"]["serve"])
            for _d, v in _kind_rows(f, "campaign")]
    rows.sort(key=lambda r: r["mtime"])
    return rows


def serve_guided_rows(f: Fold) -> list:
    rows = [dict(v["row"]["serve"]) for _d, v in _kind_rows(f, "guided")]
    rows.sort(key=lambda r: r["mtime"])
    return rows


def serve_shrink_rows(f: Fold, base: str) -> list:
    """Shrink rows across the whole tree: this base's rows plus every
    guided sub-index's (guided runs nest one level deeper than the
    two-level layout, which is why serve's walk path uses a full
    os.walk here)."""
    rows = [dict(v["row"]["serve"]) for _d, v in _kind_rows(f, "shrink")]
    for grel, _v in _kind_rows(f, "guided"):
        sub = fold(os.path.join(base, grel))
        if sub is None:
            continue
        for srel, sv in _kind_rows(sub, "shrink"):
            r = dict(sv["row"]["serve"])
            r["dir"] = os.path.join(grel, srel)
            rows.append(r)
    rows.sort(key=lambda r: r["dir"].split(os.sep))
    rows.sort(key=lambda r: r["mtime"], reverse=True)
    return rows


def live_candidates(base: str) -> Optional[list]:
    """Relative dirs worth statting for live.json on an SSE tick: the
    registered live rows plus folded campaigns — O(campaigns), never a
    store-wide listdir. None without an index (walk fallback)."""
    f = fold(base)
    if f is None:
        return None
    return sorted(set(f.kinds.get("live", ())) |
                  set(f.kinds.get("campaign", ())))


# -- tel readers --------------------------------------------------------------

def coverage_run_vectors(path: str) -> Optional[list]:
    """``(dir, vector)`` pairs for every indexed run under a store
    base, recursing through guided sub-indexes, dir strings joined to
    the operand exactly as os.walk would produce them. None when the
    base carries no index. Sorted by dir, matching
    tel_cli._coverage_dirs' sorted() walk."""
    f = fold(path)
    if f is None:
        return None
    out: list = []

    def _add(fobj: Fold, prefix: str) -> None:
        for rel, v in _kind_rows(fobj, "run"):
            cov = v["row"].get("cov")
            if cov is None:
                continue  # results.json unreadable at index time
            out.append((os.path.join(prefix, rel), _cov_restore(cov)))
        for grel, _v in _kind_rows(fobj, "guided"):
            gpath = os.path.join(prefix, grel)
            sub = fold(gpath)
            if sub is not None:
                _add(sub, gpath)
            else:
                # un-indexed guided subtree: targeted walk, same
                # pruning as tel_cli._coverage_dirs
                for root, dirs, files in os.walk(gpath,
                                                 followlinks=False):
                    dirs[:] = [d for d in dirs if not os.path.islink(
                        os.path.join(root, d))]
                    if "results.json" in files:
                        cov = coverage_fields(_load_json(
                            os.path.join(root, "results.json")))
                        if cov is not None:
                            out.append((root, cov))
                        dirs[:] = []

    _add(f, path)
    out.sort(key=lambda t: t[0])
    return out


def run_vector(rdir: str) -> Optional[dict]:
    """One run's indexed coverage vector, looked up through its base's
    fold; None when unindexed (caller reads results.json directly)."""
    rdir = os.path.abspath(rdir)
    base = os.path.dirname(os.path.dirname(rdir))
    f = fold(base)
    if f is None:
        return None
    v = f.rows.get(("run", os.path.relpath(rdir, base)))
    if v is None:
        return None
    cov = v["row"].get("cov")
    return None if cov is None else _cov_restore(cov)


def ledger_ticks(cdir: str) -> Optional[tuple]:
    """``(ticked_traces, skipped)`` for a campaign dir from its index
    row, validated against the service.jsonl fingerprint; None on any
    mismatch (caller rescans the file)."""
    cdir = os.path.abspath(cdir)
    base = os.path.dirname(os.path.dirname(cdir))
    f = fold(base)
    if f is None:
        return None
    v = f.rows.get(("campaign", os.path.relpath(cdir, base)))
    if v is None:
        return None
    payload = v["row"].get("ledger") or {}
    if not payload.get("has_service") or payload.get("fp") is None:
        return None
    try:
        st = os.stat(os.path.join(cdir, "service.jsonl"))
    except OSError:
        return None
    if [st.st_mtime_ns, st.st_size] != payload["fp"]:
        return None
    return set(payload.get("ticked") or ()), int(
        payload.get("skipped") or 0)


def newest_guided(path: str) -> Optional[tuple]:
    """``(mtime, guided.json path)`` of the newest indexed guided
    campaign under a store base; None when unindexed or none exist."""
    f = fold(path)
    if f is None:
        return None
    cands = [(v["mtime"], os.path.join(path, rel, "guided.json"))
             for rel, v in _kind_rows(f, "guided")]
    if not cands:
        return None
    return max(cands)


# -- tel profile cache (the --diff fast path) ---------------------------------

def _hist_exact(h: Hist) -> dict:
    """Lossless Hist serialization: to_dict() rounds sum/min/max to
    9 decimals, which would break bit-identical p95s after a cache
    round-trip; json round-trips raw floats exactly."""
    return {"count": h.count, "sum": h.sum,
            "min": None if h.count == 0 else h.min,
            "max": None if h.count == 0 else h.max,
            "buckets": {str(i): c for i, c in enumerate(h.counts)
                        if c}}


def _hist_from_exact(d: dict) -> Hist:
    h = Hist()
    for k, c in (d.get("buckets") or {}).items():
        h.counts[int(k)] += int(c)
    h.count = int(d.get("count") or 0)
    h.sum = float(d.get("sum") or 0.0)
    if d.get("min") is not None:
        h.min = float(d["min"])
    if d.get("max") is not None:
        h.max = float(d["max"])
    return h


def tel_profile(path: str, scan_fn) -> dict:
    """The scan() profile of one jsonl file, served from the owning
    base's tel_cache when the (mtime_ns, size) fingerprint matches,
    populated via ``scan_fn([path])`` on a miss. Falls back to a plain
    scan when the file lives under no indexed base."""
    apath = os.path.abspath(path)
    # telemetry.jsonl / service.jsonl live in run/campaign dirs two
    # levels under their base, so the index sits three dirnames up
    base = os.path.dirname(os.path.dirname(os.path.dirname(apath)))
    if not base or not has_index(base):
        return scan_fn([path])
    rel = os.path.relpath(apath, base)
    try:
        st = os.stat(apath)
        fp = (st.st_mtime_ns, st.st_size)
    except OSError:
        return scan_fn([path])
    try:
        con = _connect(base, create=False)
    except (sqlite3.Error, OSError):
        con = None
    if con is None:
        return scan_fn([path])
    try:
        try:
            got = con.execute(
                "SELECT mtime_ns, size, profile FROM tel_cache "
                "WHERE path=?", (rel,)).fetchone()
        except sqlite3.Error:
            got = None
        if got and (got[0], got[1]) == fp:
            try:
                blob = json.loads(got[2])
                return {
                    "files": 1,
                    "records": int(blob["records"]),
                    "skipped": int(blob["skipped"]),
                    "spans": {n: _hist_from_exact(d)
                              for n, d in blob["spans"].items()},
                    "hists": {n: _hist_from_exact(d)
                              for n, d in blob["hists"].items()},
                    "counters": dict(blob["counters"]),
                    "traces": set(blob["traces"]),
                }
            except (KeyError, TypeError, ValueError):
                pass  # unreadable cache row: rescan below
        prof = scan_fn([path])
        blob = json.dumps({
            "records": prof["records"], "skipped": prof["skipped"],
            "spans": {n: _hist_exact(h)
                      for n, h in prof["spans"].items()},
            "hists": {n: _hist_exact(h)
                      for n, h in prof["hists"].items()},
            "counters": prof["counters"],
            "traces": sorted(prof["traces"]),
        }, sort_keys=True)
        try:
            con.execute("BEGIN IMMEDIATE")
            con.execute(
                "INSERT OR REPLACE INTO tel_cache "
                "(path, mtime_ns, size, profile) VALUES (?, ?, ?, ?)",
                (rel, fp[0], fp[1], blob))
            con.execute("COMMIT")
        except (sqlite3.Error, OSError):
            try:
                con.execute("ROLLBACK")
            except sqlite3.Error:
                pass
        return prof
    finally:
        con.close()


# -- retention compaction -----------------------------------------------------

def compact(base: str, keep: int = COMPACT_KEEP_NEWEST,
            dry_run: bool = False) -> dict:
    """Demote old PASSING runs to index rows + summary files: delete
    everything in the run dir except results.json/test.json (and a
    shrink.json, which only failing runs carry anyway). The newest
    ``keep`` runs are spared regardless of verdict; failing or
    unknown-verdict runs are NEVER touched — their full artifacts are
    the evidence. Stored index rows (including mtimes) are left
    byte-identical; only the ``compacted`` flag flips."""
    if not has_index(base):
        rebuild(base)
    f = fold(base)
    runs = [(v["mtime"], rel, v) for rel, v in _kind_rows(f, "run")
            if not v["compacted"]]
    runs.sort(key=lambda t: (t[0], t[1].split(os.sep)))
    candidates = runs[:-keep] if keep > 0 else runs
    compacted, skipped_failures = [], 0
    removed_files = 0
    for _mtime, rel, v in candidates:
        if v["row"]["serve"].get("valid?") is not True:
            skipped_failures += 1
            continue
        rdir = os.path.join(base, rel)
        if not os.path.isdir(rdir):
            continue
        if not dry_run:
            for fn in sorted(os.listdir(rdir)):
                if fn in COMPACT_KEEP:
                    continue
                p = os.path.join(rdir, fn)
                try:
                    if os.path.islink(p) or os.path.isfile(p):
                        os.unlink(p)
                    elif os.path.isdir(p):
                        shutil.rmtree(p, ignore_errors=True)
                    removed_files += 1
                except OSError:
                    pass
        compacted.append(rel)
    if compacted and not dry_run:
        try:
            con = _connect(base, create=False)
            con.execute("BEGIN IMMEDIATE")
            seq = _next_seq(con)
            for rel in compacted:
                con.execute(
                    "UPDATE rows SET compacted=1, seq=? "
                    "WHERE kind='run' AND dir=?", (seq, rel))
                seq += 1
            con.execute("COMMIT")
            con.close()
        except (sqlite3.Error, OSError):
            pass
    _counter("store.compacted", len(compacted))
    _counter("store.compact_skipped_failures", skipped_failures)
    return {"ok": True, "base": base, "compacted": len(compacted),
            "compacted_dirs": compacted,
            "skipped_failures": skipped_failures,
            "kept_newest": min(keep, len(runs)) if keep > 0 else 0,
            "removed_entries": removed_files, "dry_run": dry_run}


# -- the `store` CLI subcommand ----------------------------------------------

def cli_store(args) -> int:
    """``python -m jepsen_etcd_tpu store {index,compact}`` — the
    operator surface: backfill/verify the index, or run a retention
    pass. Dispatched by cli.main before any jax import."""
    from . import telemetry
    tel = telemetry.Telemetry(None)
    telemetry.set_current(tel)
    try:
        base = args.store
        if args.action == "index":
            out = rebuild(base) if args.rebuild else verify(base)
        else:
            out = compact(base, keep=args.keep, dry_run=args.dry_run)
        out = dict(out,
                   counters=dict((tel.summary() or {})
                                 .get("counters") or {}))
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0 if out.get("ok") else 1
    finally:
        telemetry.set_current(telemetry.NULL)
        tel.close()
