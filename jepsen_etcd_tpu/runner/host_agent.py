"""Worker host agents: fan campaign runs across processes over TCP.

ROADMAP direction #4 inverts the reference's control-node shape — many
generator hosts feeding one TPU-mesh checking service. This module is
the generator-host half: the campaign driver raises a
:class:`HostAgentPool` (a loopback TCP registrar), spawns one
``worker-agent`` process per simulated host (``python -m
jepsen_etcd_tpu worker-agent --connect tcp://... --host hostB``), and
drives specs at whichever agents are registered. Each agent runs the
same ``campaign._pool_run`` a ProcessPoolExecutor worker would, but
over the wire: it announces itself with the ``JET-HOST`` preamble,
authenticates with the campaign's shared-secret token, stamps every
row with its host name, and heartbeats while a run is in flight so the
driver can tell slow from dead.

Fault posture mirrors the checker client: a dead or torn agent
connection re-queues the spec (``campaign.agent_requeues``) for the
surviving agents, with a requeue cap so a poisonous spec cannot
ping-pong forever — past the cap (and for any specs stranded when
every agent has died) the driver runs the spec inline itself, so a
campaign always completes.

Transport framing is ``runner/transport.py``; pool<->agent frames are
pure JSON (specs and summary rows — packed histories never cross this
link; those go agent -> checker service directly). Wall-clock here is
process supervision and socket I/O, never verdict input
(DET-allowlisted in lint/policy.py); every shared attribute a worker
thread touches is written under ``self._cv``.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import subprocess
import sys
import threading
from collections import deque
from typing import Optional

from . import telemetry
from .checker_service import ENV_HOST, ENV_TOKEN
from .transport import FrameReader, connect, listen_tcp, send_frame, \
    send_preamble

logger = logging.getLogger("jepsen_etcd_tpu.host_agent")

#: how many times a spec may be re-queued after agent deaths before
#: the driver gives up on the fleet and runs it inline
REQUEUE_CAP = 2

#: agent-side heartbeat cadence while connected (seconds); the pool's
#: idle timeout must comfortably exceed this
BEAT_S = 1.0

#: pool-side max silence from an agent before it is declared dead
#: (>> BEAT_S: heartbeats keep a healthy-but-slow run alive)
IDLE_TIMEOUT_S = 20.0


def _jframe(sock: socket.socket, wlock, obj: dict) -> None:
    """Send one JSON object as a frame, serialized under the writer
    lock (the beat thread and the run loop share the socket)."""
    data = json.dumps(obj, default=repr).encode()
    with wlock:
        send_frame(sock, data)


class _Agent:
    """Pool-side state for one registered worker agent."""

    __slots__ = ("sock", "reader", "host", "wlock")

    def __init__(self, sock: socket.socket, reader: FrameReader,
                 host: str):
        self.sock = sock
        self.reader = reader
        self.host = host
        self.wlock = threading.Lock()


class HostAgentPool:
    """The campaign driver's agent registrar + dispatcher.

    ``start()`` binds a loopback TCP listener (``self.endpoint`` is
    what agents dial); ``spawn_local`` forks worker-agent processes
    for CI's faked multi-host topology; ``run`` drives a spec list at
    every registered agent concurrently and funnels finished rows
    through a single callback.
    """

    def __init__(self, token: Optional[str] = None,
                 tel: Optional[telemetry.Telemetry] = None,
                 idle_timeout: float = IDLE_TIMEOUT_S,
                 requeue_cap: int = REQUEUE_CAP):
        self.token = token
        self.tel = tel
        self.idle_timeout = idle_timeout
        self.requeue_cap = requeue_cap
        self.endpoint: Optional[str] = None
        self._cv = threading.Condition()
        self._agents: list[_Agent] = []
        self._procs: list[subprocess.Popen] = []
        self._work: deque = deque()
        self._stranded: list[dict] = []
        self._threads: list[threading.Thread] = []
        self._listener: Optional[socket.socket] = None
        self._closed = False
        self.requeues = 0

    # ---- lifecycle ---------------------------------------------------------

    def start(self) -> "HostAgentPool":
        ls, endpoint = listen_tcp(True)
        ls.settimeout(0.25)  # poll the closed flag; close() never hangs
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="agent-pool-accept")
        with self._cv:
            self._listener = ls
            self.endpoint = endpoint
            self._threads.append(t)
        t.start()
        return self

    def close(self) -> None:
        with self._cv:
            if self._closed:
                return
            self._closed = True
            agents = list(self._agents)
            self._agents = []
            procs = list(self._procs)
            ls = self._listener
            threads = list(self._threads)
            self._cv.notify_all()
        for a in agents:
            try:
                _jframe(a.sock, a.wlock, {"op": "stop"})
            except OSError:
                pass
            try:
                a.sock.close()
            except OSError:
                pass
        if ls is not None:
            try:
                ls.close()
            except OSError:
                pass
        for t in threads:
            t.join(timeout=10)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.terminate()
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()

    # ---- fleet ------------------------------------------------------------

    def spawn_local(self, hosts: list[str]) -> list:
        """CI's faked multi-host topology: one spawned worker-agent
        process per host name, all dialing this pool over loopback.
        The auth token travels via the environment, never argv (argv
        is world-readable in /proc)."""
        env = dict(os.environ)
        if self.token:
            env[ENV_TOKEN] = self.token
        procs = []
        for h in hosts:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "jepsen_etcd_tpu", "worker-agent",
                 "--connect", self.endpoint, "--host", h],
                env=env))
        with self._cv:
            self._procs.extend(procs)
        return procs

    def wait_ready(self, n: int, timeout: float = 120.0) -> int:
        """Block until ``n`` agents have registered (or the deadline
        passes); returns the registered count."""
        with self._cv:
            self._cv.wait_for(
                lambda: len(self._agents) >= n or self._closed,
                timeout=timeout)
            return len(self._agents)

    def hosts(self) -> list[str]:
        with self._cv:
            return sorted(a.host for a in self._agents)

    # ---- registration ------------------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            with self._cv:
                if self._closed:
                    return
                ls = self._listener
            try:
                sock, _ = ls.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed by close()
            try:
                self._register(sock)
            except (OSError, ValueError, json.JSONDecodeError):
                logger.warning("agent registration failed", exc_info=True)
                try:
                    sock.close()
                except OSError:
                    pass

    def _register(self, sock: socket.socket) -> None:
        sock.settimeout(5.0)
        reader = FrameReader(sock)
        host = reader.read_preamble()
        frame = reader.recv_frame()
        if frame is None:
            raise ValueError("agent closed before registering")
        msg = json.loads(frame)
        if msg.get("op") != "register":
            raise ValueError(f"expected register, got {msg.get('op')!r}")
        if self.token and msg.get("token") != self.token:
            send_frame(sock, json.dumps(
                {"error": "bad auth token"}).encode())
            raise ValueError("agent auth token mismatch")
        host = str(msg.get("host") or host or "agent")
        send_frame(sock, json.dumps({"ok": True}).encode())
        sock.settimeout(self.idle_timeout)
        agent = _Agent(sock, reader, host)
        with self._cv:
            if self._closed:
                raise ValueError("pool closed")
            self._agents.append(agent)
            self._cv.notify_all()
        logger.info("agent %s registered", host)

    # ---- dispatch ----------------------------------------------------------

    def run(self, specs: list[dict], row_cb) -> None:
        """Drive every spec to completion: registered agents pull from
        a shared queue concurrently; specs stranded by agent deaths
        (or a fleet of zero agents) run inline in this process. Every
        finished row goes through ``row_cb`` exactly once, serialized
        under one lock."""
        cb_lock = threading.Lock()

        def _cb(row: dict) -> None:
            with cb_lock:
                row_cb(row)

        with self._cv:
            self._work = deque(specs)
            self._stranded = []
            agents = list(self._agents)
        drivers = []
        for a in agents:
            t = threading.Thread(target=self._drive, args=(a, _cb),
                                 daemon=True,
                                 name=f"agent-drive-{a.host}")
            drivers.append(t)
            t.start()
        for t in drivers:
            t.join()
        # whatever the fleet could not finish, the driver runs itself:
        # a campaign must complete even if every agent died
        with self._cv:
            leftovers = list(self._work) + list(self._stranded)
            self._work = deque()
            self._stranded = []
        if leftovers:
            from .campaign import _pool_run
            logger.warning("running %d stranded specs inline",
                           len(leftovers))
            for spec in leftovers:
                _cb(_pool_run(spec))

    def _drive(self, agent: _Agent, cb) -> None:
        """One agent's feeder thread: pull a spec, run it remotely,
        repeat; on agent death re-queue the spec and retire."""
        while True:
            with self._cv:
                if self._closed or not self._work:
                    return
                spec = self._work.popleft()
            row = self._run_on_agent(agent, spec)
            if row is None:
                with self._cv:
                    n = int(spec.get("_requeues", 0))
                    spec["_requeues"] = n + 1
                    if n < self.requeue_cap:
                        self._work.appendleft(spec)
                    else:
                        self._stranded.append(spec)
                    self.requeues += 1
                if self.tel is not None:
                    self.tel.counter("campaign.agent_requeues")
                logger.warning("agent %s died; spec %s re-queued",
                               agent.host, spec.get("index"))
                try:
                    agent.sock.close()
                except OSError:
                    pass
                return
            cb(row)

    def _run_on_agent(self, agent: _Agent, spec: dict) -> Optional[dict]:
        """Ship one spec to an agent and wait for its row, skipping
        heartbeat frames; None means the agent is dead (the caller
        re-queues)."""
        opts = dict(spec["opts"])
        opts["host_id"] = agent.host
        wire_spec = dict(spec)
        wire_spec["opts"] = opts
        try:
            _jframe(agent.sock, agent.wlock,
                    {"op": "run", "spec": wire_spec})
            while True:
                frame = agent.reader.recv_frame()
                if frame is None:
                    return None  # clean EOF: agent exited
                msg = json.loads(frame)
                if "heartbeat" in msg:
                    continue  # alive, still working
                if msg.get("op") == "row":
                    row = msg["row"]
                    row.setdefault("host", agent.host)
                    return row
                logger.warning("agent %s sent unexpected frame %r",
                               agent.host, msg.get("op"))
        except (OSError, ValueError, json.JSONDecodeError):
            # socket.timeout (idle: no heartbeat for idle_timeout),
            # TornFrame, reset, garbage — all mean the same thing here
            return None


# ---- agent side ------------------------------------------------------------


def agent_main(endpoint: str, host: str,
               token: Optional[str] = None,
               beat_s: float = BEAT_S) -> int:
    """One worker-agent process: register with the pool, then loop
    run-spec -> row until told to stop. ``ENV_HOST`` is exported so
    every CheckerClient this process opens attributes itself as
    ``host`` (the JET-HOST preamble + ``service.host_submitted.*``)."""
    token = token if token is not None else os.environ.get(ENV_TOKEN)
    os.environ[ENV_HOST] = host
    sock = connect(endpoint, timeout=10.0)
    wlock = threading.Lock()
    send_preamble(sock, host)
    _jframe(sock, wlock, {"op": "register", "host": host, "token": token})
    reader = FrameReader(sock)
    frame = reader.recv_frame()
    resp = json.loads(frame) if frame else {}
    if not resp.get("ok"):
        logger.error("agent %s rejected by pool: %s", host,
                     resp.get("error", "connection closed"))
        return 1
    sock.settimeout(None)  # runs arrive whenever the driver is ready
    logger.info("agent %s registered with %s", host, endpoint)
    stop = threading.Event()

    def _beat() -> None:
        k = 0
        while not stop.wait(beat_s):
            k += 1
            try:
                _jframe(sock, wlock, {"heartbeat": k})
            except OSError:
                return  # pool gone; the main loop will see EOF too

    threading.Thread(target=_beat, daemon=True,
                     name=f"agent-beat-{host}").start()
    try:
        while True:
            frame = reader.recv_frame()
            if frame is None:
                break  # pool closed the link: shut down
            msg = json.loads(frame)
            op = msg.get("op")
            if op == "stop":
                break
            if op != "run":
                logger.warning("agent %s: unknown op %r", host, op)
                continue
            # lazy import: jax (and the compile cache) initialize on
            # the first actual run, not at registration
            from .campaign import _pool_run
            row = _pool_run(msg["spec"])
            row["host"] = host
            _jframe(sock, wlock, {"op": "row", "row": row})
    except (OSError, ValueError, json.JSONDecodeError):
        logger.warning("agent %s: pool link died", host, exc_info=True)
        return 1
    finally:
        stop.set()
        try:
            sock.close()
        except OSError:
            pass
    return 0
