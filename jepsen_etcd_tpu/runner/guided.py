"""Coverage-guided fault campaigns: an evolutionary scheduler over the
test-all matrix.

The uniform campaign samples workload × nemesis × seed cells blindly;
this driver spends the same run budget adaptively. Generation 0
stratifies one run per matrix cell (``campaign_specs`` with
``runs_per_cell=1`` — every cell is novel by definition), then each
later generation mutates/crosses over a corpus of *interesting*
ancestors:

- **Scoring** reuses ``tel_cli.coverage``'s per-run feature vector
  verbatim (verdict signature + peak frontier width, rung escalations,
  host spills). A run earns corpus membership by showing a NEW verdict
  signature, pushing a feature dimension outside the seen envelope, or
  visiting an unseen cell. Infrastructure errors (no checker verdict)
  score zero — guided search never chases harness noise.
- **Mutations** act on the explicit nemesis schedule (materialized via
  ``simbatch.default_schedule`` when a run carried only drawn cycles):
  add/remove/retime windows, swap the partition shape, perturb the
  drop-probability/latency knobs, reseed, or cross over two ancestors
  (workload+seed from one, fault plan from the other). All draws come
  from ONE campaign-seeded ``np.random.default_rng`` so a master seed
  fully determines the search.
- **Execution** is the existing fleet, unchanged: each generation is
  one ``run_campaign`` wave (pool / host agents / checker service all
  apply), nested under the guided store dir as ``gen0, gen1, ...``.

Every failing run whose signature is newly seen is handed to
``runner/shrink.py``; the minimized schedule lands as ``shrink.json``
in that run's store dir. The driver's own summary — corpus, novel
signatures, per-run ledger, minimized repros — is ``guided.json``,
surfaced by ``/aggregate`` and ``tel --corpus``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import numpy as np

from ..simbatch import BatchConfig, default_schedule, schedule_span
from .campaign import _batchable, campaign_specs, run_campaign
from .shrink import shrink_run
from .store import _scrub, link_latest, make_store_dir
from .telemetry import Telemetry

#: partition start values guided can swap in (nemesis/faults.py shapes)
PARTITION_SHAPES = ("majority", "primaries", "majorities-ring",
                    "bridge", "one-way")
#: drop-probability / latency-delta pools for knob perturbation
DROP_PROBS = (0.01, 0.05, 0.1)
LATENCIES_MS = (8.0, 16.0, 32.0, 64.0, 128.0)

#: corpus size cap: lowest-scoring ancestors fall off first
CORPUS_CAP = 32

#: imported-ancestor aging: ``--corpus-in`` ancestors enter with
#: ``run: 0`` so cap eviction prefers dropping them on ties, but a
#: high-scoring ancestor could otherwise dominate mutation draws
#: forever. Their EFFECTIVE score halves every this-many generations
#: survived (native corpus entries never decay — they earned their
#: score in this search), and ancestors decayed below 1 leave the
#: mutation draw pool entirely.
IMPORT_HALF_LIFE_GENS = 4

#: feature-vector dimensions folded into the novelty envelope (the
#: tel_cli.coverage vector keys, reused verbatim). "waves" is BFS
#: wave depth (wgl.waves, mode=max): histories that force deeper
#: ladders are novel even at the same frontier width
ENVELOPE_DIMS = ("frontier", "waves", "rungs", "spills")

#: workload-parameter pools the "param" mutation hops along — each hop
#: moves one step within a pool, so mutants explore key churn
#: (ops_per_key rotates keys in), request rate, and client concurrency
#: without teleporting across the space
PARAM_POOLS = {
    "ops_per_key": (64, 128, 200, 400),
    "rate": (50.0, 100.0, 200.0, 400.0, 800.0),
    "concurrency": (4, 8, 10, 16, 32),
}


def _copy_opts(opts: dict) -> dict:
    return json.loads(json.dumps(_scrub(opts)))


class GuidedScheduler:
    """Deterministic candidate source: stratified seeding, then
    corpus-driven mutation/crossover. Pure bookkeeping — it never runs
    anything, so unit tests can pin its output spec-for-spec."""

    def __init__(self, base_opts: dict, workloads: list, nemeses: list,
                 *, seed0: int = 0, master_seed: Optional[int] = None,
                 corpus_cap: int = CORPUS_CAP):
        self.base = _copy_opts(base_opts)
        self.workloads = list(workloads)
        self.nemeses = [list(n) for n in nemeses]
        self.master_seed = int(seed0 if master_seed is None
                               else master_seed)
        self.rng = np.random.default_rng(self.master_seed)
        self._pending = [s["opts"] for s in campaign_specs(
            self.base, self.workloads, self.nemeses, 1, seed0)]
        self.next_seed = seed0 + len(self._pending)
        self.corpus: list[dict] = []
        self.corpus_cap = corpus_cap
        self.seen_signatures: dict[str, int] = {}
        self.seen_cells: set = set()
        self.envelope = {dim: 0 for dim in ENVELOPE_DIMS}
        #: wgl.rung_waves histogram buckets already observed — each
        #: newly-occupied bucket is a fresh search-depth shape (+1)
        self.seen_wave_buckets: set = set()
        #: generation counter: stamps corpus entries (``born``) so
        #: imported-ancestor decay ages in generations survived
        self.wave = 0
        self.runs_observed = 0
        self.mutations = 0
        self.crossovers = 0
        #: imported ancestors evicted after a full generation below
        #: effective score 1 (ROADMAP #2 aging residual)
        self.corpus_retired = 0

    # -- candidate generation ----------------------------------------

    def next_generation(self, size: int) -> list:
        """Up to ``size`` opts dicts: pending stratified cells first,
        then mutants/crossovers of corpus ancestors."""
        self.wave += 1
        self._retire_stale()
        out = []
        while self._pending and len(out) < size:
            out.append(self._pending.pop(0))
        while len(out) < size:
            out.append(self._mutate())
        return out

    def _mint_seed(self) -> int:
        s = self.next_seed
        self.next_seed += 1
        return s

    def _random_cell(self) -> dict:
        rng = self.rng
        wl = self.workloads[int(rng.integers(len(self.workloads)))]
        nem = self.nemeses[int(rng.integers(len(self.nemeses)))]
        opts = dict(self.base)
        opts.update({"workload": wl, "nemesis": list(nem),
                     "seed": self._mint_seed()})
        return opts

    def _eff_score(self, c: dict) -> float:
        """Eviction/draw weight: native entries keep their earned
        score; imported ancestors decay by half every
        ``IMPORT_HALF_LIFE_GENS`` generations survived since import."""
        score = float(c.get("score") or 0)
        if not c.get("imported"):
            return score
        age = max(0, self.wave - int(c.get("born") or 0))
        return score * 0.5 ** (age // IMPORT_HALF_LIFE_GENS)

    def _evict(self) -> None:
        if len(self.corpus) > self.corpus_cap:
            self.corpus.sort(
                key=lambda c: (-self._eff_score(c), c["run"]))
            del self.corpus[self.corpus_cap:]

    def _retire_stale(self) -> None:
        """Retire imported ancestors whose effective score has sat
        below 1 for a FULL generation. ``_pick`` already excludes them
        from mutation draws, but under the cap they lingered in the
        corpus (and its artifact) forever; one grace generation lets
        an entry whose decay step lands mid-generation still be drawn
        before it goes."""
        kept = []
        for c in self.corpus:
            if not c.get("imported") or self._eff_score(c) >= 1.0:
                c.pop("stale_since", None)
                kept.append(c)
                continue
            since = c.get("stale_since")
            if since is None:
                c["stale_since"] = self.wave
                kept.append(c)
            elif self.wave - int(since) < 1:
                kept.append(c)
            else:
                self.corpus_retired += 1
        self.corpus[:] = kept

    def _pick(self) -> dict:
        # stale imported ancestors (effective score decayed below 1)
        # never retire natives from the cap, but they DO stop feeding
        # mutation draws; an all-stale corpus still draws uniformly
        pool = [c for c in self.corpus
                if not c.get("imported") or self._eff_score(c) >= 1.0]
        pool = pool or self.corpus
        return pool[int(self.rng.integers(len(pool)))]

    def _mutate(self) -> dict:
        rng = self.rng
        self.mutations += 1
        if not self.corpus:
            return self._random_cell()
        if len(self.corpus) >= 2 and rng.random() < 0.25:
            return self._crossover()
        anc = self._pick()
        opts = _copy_opts(anc["opts"])
        nem = list(opts.get("nemesis") or ())
        ops = ["reseed", "cell", "param"]
        if nem and _batchable(opts):
            ops += ["window"] * 3
            if "partition" in nem:
                ops.append("shape")
            ops.append("knob")
        op = ops[int(rng.integers(len(ops)))]
        if op == "reseed":
            opts["seed"] = self._mint_seed()
        elif op == "cell":
            nem2 = self.nemeses[int(rng.integers(len(self.nemeses)))]
            opts["nemesis"] = list(nem2)
            opts.pop("nem_schedule", None)  # kinds may no longer match
            opts["seed"] = self._mint_seed()
        elif op == "window":
            self._mutate_schedule(opts)
        elif op == "shape":
            opts["nem_partition_shape"] = str(
                PARTITION_SHAPES[int(rng.integers(
                    len(PARTITION_SHAPES)))])
        elif op == "knob":
            if rng.random() < 0.5:
                opts["nem_drop_prob"] = float(
                    DROP_PROBS[int(rng.integers(len(DROP_PROBS)))])
            else:
                opts["nem_latency_ms"] = float(
                    LATENCIES_MS[int(rng.integers(len(LATENCIES_MS)))])
        elif op == "param":
            self._hop_param(opts)
        return opts

    def _hop_param(self, opts: dict) -> None:
        """One step along a workload-parameter pool (PARAM_POOLS):
        snap the current value to its nearest pool entry, then hop one
        slot up or down. Drawn schedules key off (config, seed), so a
        rate/concurrency hop also reshapes the fault plan timing —
        that interplay is exactly what the dimension is for."""
        rng = self.rng
        names = sorted(PARAM_POOLS)
        name = names[int(rng.integers(len(names)))]
        pool = PARAM_POOLS[name]
        cur = opts.get(name)
        if cur is None:
            cur = self.base.get(name)
        try:
            i = min(range(len(pool)),
                    key=lambda j: abs(float(pool[j]) - float(cur)))
        except (TypeError, ValueError):
            i = int(rng.integers(len(pool)))
        step = 1 if rng.random() < 0.5 else -1
        i = min(len(pool) - 1, max(0, i + step))
        opts[name] = pool[i]

    def _materialize(self, opts: dict) -> list:
        """The explicit window list a mutant starts from: the opts' own
        schedule, else the drawn cycles of (config, seed)."""
        sched = opts.get("nem_schedule")
        if sched is None:
            cfg = BatchConfig.from_opts(opts)
            sched = default_schedule(cfg, int(opts.get("seed", 0)))
        return [list(w) for w in sched]

    def _mutate_schedule(self, opts: dict) -> None:
        rng = self.rng
        sched = self._materialize(opts)
        span = schedule_span(BatchConfig.from_opts(opts))
        kinds = list(opts.get("nemesis") or ())
        which = rng.random()
        if which < 0.4 or not sched:  # add a window
            start = int(rng.integers(1, max(2, span)))
            hold = int(rng.integers(max(1, span // 12),
                                    max(2, span // 4)))
            kind = kinds[int(rng.integers(len(kinds)))]
            sched.append([start, kind, hold])
        elif which < 0.7:  # drop a window
            sched.pop(int(rng.integers(len(sched))))
        else:  # retime a window
            w = sched[int(rng.integers(len(sched)))]
            if rng.random() < 0.5:
                w[0] = max(1, int(w[0] * rng.uniform(0.5, 1.5)))
            else:
                w[2] = max(1, int(w[2] * rng.uniform(0.5, 1.5)))
        sched.sort(key=lambda w: (w[0], w[2]))
        opts["nem_schedule"] = sched

    def _crossover(self) -> dict:
        """Workload+seed from one ancestor, fault plan (nemesis list,
        schedule, knobs) from another."""
        self.crossovers += 1
        a, b = self._pick(), self._pick()
        opts = _copy_opts(a["opts"])
        donor = _copy_opts(b["opts"])
        opts["nemesis"] = list(donor.get("nemesis") or ())
        for k in ("nem_schedule", "nem_partition_shape",
                  "nem_latency_ms", "nem_drop_prob"):
            if donor.get(k) is not None:
                opts[k] = donor[k]
            else:
                opts.pop(k, None)
        return opts

    # -- corpus transfer ----------------------------------------------

    def export_corpus(self) -> dict:
        """JSON-able snapshot of the search state worth carrying into
        the NEXT campaign: the ancestor corpus, the novelty envelope,
        and the seen signature/cell ledgers (so a warmed-up search
        only scores genuinely new behavior), plus the seed cursor (so
        freshly minted seeds never collide with imported ancestors)."""
        return {
            "schema": 1, "kind": "guided-corpus",
            "master_seed": self.master_seed,
            "next_seed": self.next_seed,
            "envelope": dict(self.envelope),
            "signatures": dict(self.seen_signatures),
            "cells": sorted([w, list(n)] for w, n in self.seen_cells),
            "wave_buckets": sorted(self.seen_wave_buckets),
            "corpus": [dict(c) for c in self.corpus],
        }

    def import_corpus(self, data: dict) -> int:
        """Merge an :meth:`export_corpus` payload: ancestors join the
        pool (the cap still applies), the envelope widens to the
        imported peaks, and imported signatures/cells stop scoring as
        novel. Returns the number of ancestors added. Unknown envelope
        dims in the payload are dropped; missing ones default to 0, so
        corpora survive dimension growth across versions."""
        if not isinstance(data, dict) \
                or data.get("kind") != "guided-corpus":
            raise ValueError(
                "not a guided-corpus export (produce one with "
                "campaign --guided --corpus-out PATH)")
        self.next_seed = max(self.next_seed,
                             int(data.get("next_seed") or 0))
        env = data.get("envelope") or {}
        for dim in ENVELOPE_DIMS:
            v = int(env.get(dim) or 0)
            if v > self.envelope[dim]:
                self.envelope[dim] = v
        for sig, run in (data.get("signatures") or {}).items():
            self.seen_signatures.setdefault(str(sig), int(run))
        for cell in data.get("cells") or ():
            if isinstance(cell, (list, tuple)) and len(cell) == 2:
                self.seen_cells.add((cell[0], tuple(cell[1] or ())))
        for b in data.get("wave_buckets") or ():
            self.seen_wave_buckets.add(int(b))
        added = 0
        for c in data.get("corpus") or ():
            if not (isinstance(c, dict) and isinstance(c.get("opts"),
                                                       dict)):
                continue
            self.corpus.append({
                "opts": _copy_opts(c["opts"]),
                "seed": c.get("seed"),
                "run": 0,               # pre-history: ties sort first
                "score": int(c.get("score") or 1),
                "signature": c.get("signature") or "",
                "vector": {dim: int((c.get("vector") or {})
                                    .get(dim) or 0)
                           for dim in ENVELOPE_DIMS},
                "imported": True,
                # decay clock starts at the CURRENT wave: an ancestor
                # ages by generations survived here, not by how old
                # the exporting campaign was
                "born": self.wave,
            })
            added += 1
        self._evict()
        return added

    # -- scoring ------------------------------------------------------

    def observe(self, opts: dict, row: dict,
                vector: Optional[dict]) -> int:
        """Score one finished run by coverage novelty; admit scoring
        runs to the corpus. Returns the score (0 = not interesting).

        Rows without a real checker verdict (agent errors, requeues,
        crashed epilogues) always score 0: harness noise must not
        steer the search."""
        self.runs_observed += 1
        cell = (row.get("workload"), tuple(row.get("nemesis") or ()))
        if row.get("status") != "done" or not vector:
            return 0
        score = 0
        sig = vector.get("signature") or ""
        if sig and sig not in self.seen_signatures:
            self.seen_signatures[sig] = self.runs_observed
            score += 4
        for dim in ENVELOPE_DIMS:
            v = int(vector.get(dim) or 0)
            if v > self.envelope[dim]:
                self.envelope[dim] = v
                score += 1
        # search-depth SHAPE, not just envelope peaks: each
        # wgl.rung_waves histogram bucket (one per ladder rung) first
        # occupied by this run is novel — a history that makes many
        # dispatches settle at a new rung scores even when the deepest
        # rung (the "rungs" envelope dim) has been seen before
        for b in vector.get("wave_hist") or {}:
            if int(b) not in self.seen_wave_buckets:
                self.seen_wave_buckets.add(int(b))
                score += 1
        if cell not in self.seen_cells:
            self.seen_cells.add(cell)
            score += 1
        if score:
            self.corpus.append({
                "opts": _copy_opts(opts), "seed": row.get("seed"),
                "run": self.runs_observed, "score": score,
                "signature": sig,
                "vector": {dim: int(vector.get(dim) or 0)
                           for dim in ENVELOPE_DIMS},
                "born": self.wave,
            })
            self._evict()
        return score


def run_guided(base_opts: dict, workloads: list, nemeses: list, *,
               budget: int, seed0: int = 0,
               master_seed: Optional[int] = None,
               pool: int = 0, service: bool = False,
               service_tick_s: float = 0.05,
               store_base: str = "store", name: str = "guided",
               start_method: str = "spawn", live: bool = False,
               hosts=None, shrink: bool = True, max_shrinks: int = 4,
               gen_size: Optional[int] = None, on_row=None,
               corpus_in: Optional[str] = None,
               corpus_out: Optional[str] = None) -> dict:
    """Drive a guided campaign of ``budget`` runs; returns (and writes
    as ``<guided dir>/guided.json``) the search summary.

    Each generation executes as one :func:`run_campaign` wave nested
    under the guided store dir, so the pool / checker-service /
    host-agent fleet applies unchanged. Batched re-execution wants the
    lockstep generator, so ``gen_epoch`` defaults to epoch-v2 here."""
    from ..tel_cli import coverage

    t0 = time.monotonic()
    base = _copy_opts(base_opts)
    base.setdefault("gen_epoch", "epoch-v2")
    gdir = make_store_dir(store_base, name)
    trace = f"{name}-{os.path.basename(gdir)}"
    tel = Telemetry(os.path.join(gdir, "telemetry.jsonl"), trace=trace)
    sched = GuidedScheduler(base, workloads, nemeses, seed0=seed0,
                            master_seed=master_seed)
    imported = 0
    if corpus_in:
        with open(corpus_in) as f:
            imported = sched.import_corpus(json.load(f))
        tel.counter("guided.corpus-imported", imported)
    ledger: list[dict] = []
    minimized: list[dict] = []
    first_failure: Optional[int] = None
    gen = 0
    runs_left = int(budget)
    try:
        while runs_left > 0:
            want = min(runs_left,
                       len(sched._pending) or gen_size
                       or max(2, len(sched._pending) or 4))
            specs = [{"index": i, "opts": o} for i, o in
                     enumerate(sched.next_generation(want))]
            if not specs:
                break
            tel.counter("guided.generations")
            tel.event("guided.generation", gen=gen, size=len(specs))
            summary = run_campaign(
                specs, pool=pool, service=service,
                service_tick_s=service_tick_s, store_base=gdir,
                name=f"gen{gen}", start_method=start_method,
                live=live, hosts=hosts, on_row=on_row)
            for row in sorted((r for r in summary["runs"] if r),
                              key=lambda r: r["index"]):
                opts = specs[row["index"]]["opts"]
                rdir = row.get("dir")
                vector = None
                if rdir:
                    try:
                        cov = coverage(rdir)
                        vector = (cov["runs"] or [None])[0]
                    except Exception:
                        vector = None
                score = sched.observe(opts, row, vector)
                tel.counter("guided.runs")
                if row.get("status") != "done":
                    tel.counter("guided.errors")
                if score:
                    tel.counter("guided.novelty", score)
                sig = (vector or {}).get("signature") or ""
                failing = (row.get("status") == "done"
                           and row.get("valid") is False)
                if failing:
                    tel.counter("guided.failures")
                    if first_failure is None:
                        first_failure = sched.runs_observed
                ledger.append({
                    "run": sched.runs_observed, "gen": gen,
                    "index": row["index"],
                    "workload": row.get("workload"),
                    "nemesis": row.get("nemesis"),
                    "seed": row.get("seed"),
                    "status": row.get("status"),
                    "valid": row.get("valid"),
                    "signature": sig, "score": score,
                    "dir": rdir,
                })
                # shrink the first run of each novel failure signature
                if (shrink and failing and sig and rdir
                        and len(minimized) < max_shrinks
                        and sched.seen_signatures.get(sig)
                        == sched.runs_observed
                        and _batchable(opts)):
                    try:
                        art = shrink_run(opts, int(row.get("seed") or 0),
                                         store_dir=rdir)
                    except Exception:
                        art = None
                    if art:
                        minimized.append({
                            "dir": rdir, "run": sched.runs_observed,
                            "signature": art["signature"],
                            "original_windows": art["original_windows"],
                            "windows": art["windows"],
                            "nemesis_ops": art["nemesis_ops"],
                            "executions": art["executions"],
                            "repro": art["repro"],
                        })
            runs_left -= len(specs)
            gen += 1
        tel.counter("guided.corpus", len(sched.corpus), mode="max")
        tel.counter("guided.mutations", sched.mutations)
        tel.counter("guided.crossovers", sched.crossovers)
        tel.counter("guided.signatures", len(sched.seen_signatures))
        tel.counter("guided.corpus_retired", sched.corpus_retired)
    finally:
        out = {
            "schema": 1, "kind": "guided", "name": name, "dir": gdir,
            "budget": int(budget), "runs": sched.runs_observed,
            "generations": gen, "seed0": seed0,
            "master_seed": sched.master_seed,
            "workloads": list(workloads),
            "nemeses": [list(n) for n in nemeses],
            "signatures": dict(sched.seen_signatures),
            "envelope": dict(sched.envelope),
            "first_failure_run": first_failure,
            "corpus": sched.corpus,
            "corpus_imported": imported,
            "corpus_retired": sched.corpus_retired,
            "corpus_in": corpus_in, "corpus_out": corpus_out,
            "minimized": minimized,
            "ledger": ledger,
            "wall_s": round(time.monotonic() - t0, 3),
            "telemetry": tel.summary(),
        }
        with open(os.path.join(gdir, "guided.json"), "w") as f:
            json.dump(_scrub(out), f, indent=2, default=repr)
        try:
            # fold the finished search into its parent store's index
            from .store_index import record_guided
            record_guided(gdir)
        except Exception:
            pass
        if corpus_out:
            with open(corpus_out, "w") as f:
                json.dump(_scrub(sched.export_corpus()), f, indent=2,
                          default=repr)
        tel.close()
        link_latest(gdir)
    return out
