"""Batched TPU checker service: one process owns the device.

The campaign driver (runner/campaign.py) fans runs over a process
pool; if each run dispatched its own device checks it would pay the
~100 ms synchronized-call floor and ~57 ms/launch fixed cost per RUN
(PERF.md §1). This service is the continuous-batching answer (the
Orca/vLLM scheduler shape from PAPERS.md applied to history checking):
runner processes pack their histories ONCE (ops/wgl.py
serialize_packed, ~32 B/op compact vectors), ship them over a local
AF_UNIX socket, and the service coalesces everything pending across
all connections into one ``wgl.check_packed_batch`` call per tick —
one device dispatch per (bucket, width) group per tick, no matter how
many runs contributed keys.

Soundness contract: the service runs the exact device-path code the
in-process checker would (``check_packed_batch`` over deserialized
packs — frame tables rebuilt bit-identically by ``ensure_frames``),
and ships only the device verdicts back. Everything judgment-shaped
stays in the runner: native-DFS-sized keys never reach the socket
(checkers/tpu_linearizable.py routes them before packing), and the
runner's ``_finalize`` still runs its CPU diagnostics / overflow-DFS /
fallback ladder on the returned verdicts. A ``_resume`` payload
(device arrays frozen mid-ladder) cannot cross the socket; it is
stripped, and the runner's ``_overflow`` re-runs the spill locally —
PR 5 pinned that the spill verdict is bit-identical at every resume
budget.

Degradation contract: every client failure (no socket, connect
refused, protocol error, service-side exception) returns ``None`` from
``CheckerClient.check`` / ``client_for`` and bumps the
``service.fallback`` counter — the checker then runs the same packs
in-process, so a dead service costs latency, never verdicts.

Wire format (length-prefixed frames, 8-byte little-endian size):

    request:  {"op": "check", "id": n, "sizes": [b0, b1, ...]}\\n
              <pack0 bytes><pack1 bytes>...
    response: {"id": n, "results": [...]}        (or {"id", "error"})
    also:     {"op": "ping"|"stats", "id": n} -> JSON-only responses
"""

from __future__ import annotations

import json
import logging
import os
import socket
import struct
import tempfile
import threading
import time
from typing import Any, Optional

from . import telemetry
from .telemetry import Telemetry

logger = logging.getLogger("jepsen_etcd_tpu.checker_service")

#: env var naming the service socket; opts/test["checker_service"] wins
ENV_VAR = "JEPSEN_ETCD_TPU_CHECKER_SERVICE"

_LEN = struct.Struct("<Q")

#: refuse frames past this size (a corrupt length prefix must not
#: allocate the heap): 1 GiB >> any real campaign's per-request packs
MAX_FRAME = 1 << 30


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Optional[bytes]:
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (n,) = _LEN.unpack(head)
    if n > MAX_FRAME:
        raise ValueError(f"frame of {n} bytes exceeds MAX_FRAME")
    return _recv_exact(sock, n)


def _plain(x: Any) -> Any:
    """JSON-safe copy of a verdict dict: numpy scalars to python,
    device-array payloads (``_resume``) already stripped by callers."""
    if isinstance(x, dict):
        return {k: _plain(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_plain(v) for v in x]
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    item = getattr(x, "item", None)
    if callable(item):
        return item()
    return repr(x)


class _Request:
    """One pending check request: its packs, arrival time, the
    originating run's trace id, and the connection to answer on."""

    __slots__ = ("conn", "wlock", "req_id", "packs", "t_arrive",
                 "trace")

    def __init__(self, conn, wlock, req_id, packs, t_arrive,
                 trace=None):
        self.conn = conn
        self.wlock = wlock
        self.req_id = req_id
        self.packs = packs
        self.t_arrive = t_arrive
        self.trace = trace


#: memo for _device_name — mutated in place (idempotent value, so a
#: racing double-compute is benign and no module global is rebound)
_device_name_cache: dict = {}


def _device_name() -> str:
    """``platform+id`` of the device this service dispatches on
    (``tpu0``, ``cpu0``); the attribution key ROADMAP #3's sharded
    service will carry per shard."""
    name = _device_name_cache.get("name")
    if name is None:
        try:
            import jax
            d = jax.devices()[0]
            name = f"{d.platform}{d.id}"
        except Exception:
            name = "host0"
        _device_name_cache["name"] = name
    return name


class CheckerService:
    """The device-owning batch scheduler.

    Threads: one acceptor, one reader per connection (they only parse
    and enqueue), and ONE dispatcher that owns every device call —
    jax state is never touched from two threads. All shared state
    (pending queue, connection list, stop flag) is mutated under
    ``_cv`` only.
    """

    def __init__(self, path: Optional[str] = None,
                 tick_s: float = 0.05,
                 tel: Optional[Telemetry] = None):
        if path is None:
            path = os.path.join(
                tempfile.mkdtemp(prefix="jet-checker-"), "checker.sock")
        self.path = path
        self.tick_s = tick_s
        self.tel = tel if tel is not None else Telemetry()
        self._cv = threading.Condition()
        self._pending: list[_Request] = []
        self._conns: list[socket.socket] = []
        self._threads: list[threading.Thread] = []
        self._stopped = False
        self._listener: Optional[socket.socket] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "CheckerService":
        ls = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            os.unlink(self.path)
        except OSError:
            pass
        ls.bind(self.path)
        ls.listen(64)
        # closing a listener does NOT wake a blocked accept() on
        # Linux; poll with a short timeout so close() never hangs
        ls.settimeout(0.25)
        with self._cv:
            self._listener = ls
            acceptor = threading.Thread(
                target=self._accept_loop, name="checker-svc-accept",
                daemon=True)
            dispatcher = threading.Thread(
                target=self._dispatch_loop, name="checker-svc-dispatch",
                daemon=True)
            self._threads += [acceptor, dispatcher]
        acceptor.start()
        dispatcher.start()
        logger.info("checker service listening on %s", self.path)
        return self

    def close(self) -> None:
        with self._cv:
            if self._stopped:
                return
            self._stopped = True
            self._cv.notify_all()
            ls = self._listener
            conns = list(self._conns)
            threads = list(self._threads)
        if ls is not None:
            try:
                ls.close()
            except OSError:
                pass
        for c in conns:
            # shutdown (not just close) reliably wakes a reader
            # blocked in recv() on this connection
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in threads:
            t.join(timeout=30)
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def stats(self) -> dict:
        """The service's telemetry summary (counters + spans)."""
        return self.tel.summary()

    # -- socket side ---------------------------------------------------------
    def _accept_loop(self) -> None:
        while True:
            with self._cv:
                if self._stopped:
                    return
                ls = self._listener
            try:
                conn, _ = ls.accept()
            except socket.timeout:
                continue  # poll the stop flag
            except OSError:
                return  # listener closed by close()
            wlock = threading.Lock()
            reader = threading.Thread(
                target=self._reader, args=(conn, wlock),
                name="checker-svc-reader", daemon=True)
            with self._cv:
                if self._stopped:
                    conn.close()
                    return
                self._conns.append(conn)
                self._threads.append(reader)
            reader.start()

    def _reader(self, conn: socket.socket, wlock: threading.Lock) -> None:
        try:
            while True:
                frame = _recv_frame(conn)
                if frame is None:
                    return
                self._handle(conn, wlock, frame)
        except (OSError, ValueError) as e:
            logger.debug("checker service reader exits: %r", e)
        finally:
            with self._cv:
                if conn in self._conns:
                    self._conns.remove(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, conn, wlock, frame: bytes) -> None:
        from ..ops import wgl
        nl = frame.index(b"\n") if b"\n" in frame else len(frame)
        head = json.loads(frame[:nl].decode())
        op = head.get("op")
        if op == "ping":
            with wlock:
                _send_frame(conn, json.dumps(
                    {"id": head.get("id"), "ok": True}).encode())
            return
        if op == "stats":
            with wlock:
                _send_frame(conn, json.dumps(
                    {"id": head.get("id"),
                     "stats": self.stats()}).encode())
            return
        if op != "check":
            with wlock:
                _send_frame(conn, json.dumps(
                    {"id": head.get("id"),
                     "error": f"unknown op {op!r}"}).encode())
            return
        packs = []
        off = nl + 1
        for size in head["sizes"]:
            packs.append(wgl.deserialize_packed(frame[off:off + size]))
            off += size
        req = _Request(conn, wlock, head.get("id"), packs,
                       time.monotonic(), trace=head.get("trace"))
        self.tel.counter("service.requests")
        self.tel.counter("service.submitted", len(packs))
        with self._cv:
            self._pending.append(req)
            self._cv.notify_all()

    # -- device side ---------------------------------------------------------
    def _dispatch_loop(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._stopped:
                    self._cv.wait()
                if self._stopped and not self._pending:
                    return
            # coalescing window: let concurrently-finishing runs land
            # their submissions before the batch is frozen
            time.sleep(self.tick_s)
            with self._cv:
                batch, self._pending = self._pending, []
            if batch:
                self._run_tick(batch)

    def _run_tick(self, batch: list[_Request]) -> None:
        from ..ops import wgl
        t_start = time.monotonic()
        all_packs = []
        slots = []  # (request index, offset into its results)
        for ri, req in enumerate(batch):
            for j, p in enumerate(req.packs):
                all_packs.append(p)
                slots.append((ri, j))
        groups = {(wgl.bucket(p.R), wgl.info_dims(p), p.w)
                  for p in all_packs if p.ok and p.R > 0}
        runs = sorted({req.trace for req in batch
                       if req.trace is not None})
        dev = _device_name()
        # the device work runs under the SERVICE's telemetry (deep
        # wgl code reaches the recorder via telemetry.current()).
        # Pin it to THIS thread only: a process-global swap (the old
        # set_current/restore pair) had a window where a concurrent
        # in-process checker thread recorded into the service stream —
        # and restored a stale recorder over a newer one. The
        # thread-local pin cannot race: other threads never see it.
        telemetry.set_thread_current(self.tel)
        try:
            with self.tel.span("service.tick", packs=len(all_packs),
                               requests=len(batch),
                               groups=len(groups),
                               runs=runs, device=dev) as sp:
                try:
                    outs = wgl.check_packed_batch(all_packs)
                    err = None
                except Exception as e:  # degrade, never wedge clients
                    logger.exception("checker service tick failed")
                    outs, err = None, repr(e)
                sp.set(error=err)
        finally:
            telemetry.set_thread_current(None)
        busy = time.monotonic() - t_start
        self.tel.counter("service.ticks")
        self.tel.counter("service.group_ticks", len(groups))
        self.tel.counter("service.coalesced",
                         sum(1 for _ in all_packs) - len(groups))
        self.tel.counter("service.batch_occupancy", len(all_packs),
                         mode="max")
        self.tel.counter("service.device_busy_s." + dev,
                         round(busy, 6))
        # each request's wait is rounded ONCE and used everywhere —
        # the summed counter, the hist, and the per-request reply — so
        # per-run attribution re-sums to the service total exactly
        waits = [round(t_start - req.t_arrive, 6) for req in batch]
        self.tel.counter("service.queue_wait_s", round(sum(waits), 6))
        for w in waits:
            self.tel.hist("service.queue_wait_s", w)
        results_by_req: dict[int, list] = {
            ri: [None] * len(req.packs) for ri, req in enumerate(batch)}
        if outs is not None:
            for (ri, j), out in zip(slots, outs):
                out = dict(out)
                # frozen-frontier device arrays cannot cross the
                # socket; the runner's overflow path re-runs the spill
                # locally (bit-identical verdict, PR 5 contract)
                out.pop("_resume", None)
                results_by_req[ri][j] = _plain(out)
        for ri, req in enumerate(batch):
            if outs is None:
                payload = {"id": req.req_id, "error": err,
                           "queue_wait_s": waits[ri]}
            else:
                payload = {"id": req.req_id,
                           "results": results_by_req[ri],
                           "queue_wait_s": waits[ri]}
            try:
                with req.wlock:
                    _send_frame(req.conn, json.dumps(payload).encode())
            except OSError:
                logger.debug("checker service: client went away")


# ---------------------------------------------------------------------------
# client side (runs inside runner processes)


class ServiceUnavailable(Exception):
    pass


class CheckerClient:
    """Synchronous client: one request outstanding at a time (the
    checker blocks on its verdicts anyway). Any failure marks the
    client broken; callers fall back to in-process checking."""

    def __init__(self, path: str, timeout: float = 600.0):
        self.path = path
        self.timeout = timeout
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._next_id = 0
        self.broken = False
        #: queue wait the service attributed to the LAST check() reply
        #: (seconds); None until a reply carries one
        self.last_queue_wait_s: Optional[float] = None

    def _rpc(self, head: dict, body: bytes = b"") -> dict:
        with self._lock:
            if self.broken:
                raise ServiceUnavailable(self.path)
            try:
                if self._sock is None:
                    s = socket.socket(socket.AF_UNIX,
                                      socket.SOCK_STREAM)
                    s.settimeout(self.timeout)
                    s.connect(self.path)
                    self._sock = s
                sock = self._sock
                head = dict(head)
                head["id"] = self._next_id
                self._next_id += 1
                _send_frame(sock, json.dumps(head).encode() + b"\n"
                            + body)
                frame = _recv_frame(sock)
                if frame is None:
                    raise ServiceUnavailable("connection closed")
                resp = json.loads(frame.decode())
                if resp.get("id") != head["id"]:
                    raise ServiceUnavailable("response id mismatch")
                return resp
            except (OSError, ValueError, json.JSONDecodeError) as e:
                self.broken = True
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                raise ServiceUnavailable(repr(e)) from e

    def ping(self) -> bool:
        try:
            return bool(self._rpc({"op": "ping"}).get("ok"))
        except ServiceUnavailable:
            return False

    def stats(self) -> Optional[dict]:
        try:
            return self._rpc({"op": "stats"}).get("stats")
        except ServiceUnavailable:
            return None

    def check(self, packs: list,
              trace: Optional[str] = None) -> Optional[list]:
        """Ship packed histories; returns one verdict dict per pack
        (aligned), or None if the service failed — callers MUST then
        check the same packs in-process. ``trace`` is the originating
        run's trace id: the service stamps it on the dispatch tick
        span so the shipped-packs ledger is joinable per run."""
        from ..ops import wgl
        try:
            blobs = [wgl.serialize_packed(p) for p in packs]
            head = {"op": "check", "sizes": [len(b) for b in blobs]}
            if trace is not None:
                head["trace"] = trace
            resp = self._rpc(head, b"".join(blobs))
        except ServiceUnavailable:
            return None
        self.last_queue_wait_s = resp.get("queue_wait_s")
        results = resp.get("results")
        if results is None or len(results) != len(packs):
            # a structured error reply (a failed tick): the transport
            # is healthy, so DON'T latch broken — this call falls back
            # to in-process checking, the next may succeed again
            return None
        return results

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


#: per-process client cache; None latches "tried and broken" so a dead
#: service costs one connect attempt per process, not one per key batch
_clients: dict[str, Optional[CheckerClient]] = {}
_clients_lock = threading.Lock()


def endpoint_for(test: Any) -> Optional[str]:
    """The configured service socket for a test dict (or env), if any."""
    path = None
    if isinstance(test, dict):
        path = test.get("checker_service")
    return path or os.environ.get(ENV_VAR) or None


def client_for(test: Any) -> Optional[CheckerClient]:
    """A working (cached) client for the test's service endpoint, or
    None — absent config, failed connect, or a previously broken
    client all mean "check in-process"."""
    path = endpoint_for(test)
    if not path:
        return None
    with _clients_lock:
        if path in _clients:
            c = _clients[path]
            if c is not None and c.broken:
                _clients[path] = None
                c = None
            return c
    client = CheckerClient(path)
    ok = client.ping()
    with _clients_lock:
        _clients[path] = client if ok else None
    if not ok:
        # callers count service.fallback per degraded check; here just
        # explain the latch once
        logger.warning("checker service unreachable at %s; "
                       "checking in-process", path)
        return None
    return _clients[path]


def reset_clients() -> None:
    """Drop the per-process client cache (tests; spawn workers start
    clean anyway)."""
    with _clients_lock:
        for c in _clients.values():
            if c is not None:
                c.close()
        _clients.clear()
