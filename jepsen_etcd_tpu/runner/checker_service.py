"""Batched TPU checker service: one process owns every device.

The campaign driver (runner/campaign.py) fans runs over a process
pool; if each run dispatched its own device checks it would pay the
~100 ms synchronized-call floor and ~57 ms/launch fixed cost per RUN
(PERF.md §1). This service is the continuous-batching answer (the
Orca/vLLM scheduler shape from PAPERS.md applied to history checking):
runner processes pack their histories ONCE (ops/wgl.py
serialize_packed, ~32 B/op compact vectors), ship them over a local
AF_UNIX socket, and the service coalesces everything pending across
all connections into one ``wgl.check_packed_batch`` call per tick —
one device dispatch per (bucket, width) group per tick, no matter how
many runs contributed keys.

Multi-device dispatch (ISSUE 15): the dispatcher assigns each
(bucket, width) group to a chip with a STICKY round-robin map
(``DevicePlacement`` — a group shape always lands on the chip whose
compiled executable is warm) and hands the per-group launches to
per-device worker threads, so a v5e-8's eight chips run eight group
dispatches concurrently instead of queueing one. A tick whose packs
all share ONE group shape instead shards the batch axis of the wave
ladder over the whole mesh with shard_map (the host + device + sharded
split ops/closure.py proved). Host packing is double-buffered: while
tick N's jobs run on their chips, the dispatcher packs tick N+1's
tables (``wgl.prepare_bucket_group``), so pack_s and dispatch wall
overlap instead of serialize; on TPU the packed inputs are donated to
the launch (PERF.md §6).

Soundness contract: the service runs the exact device-path code the
in-process checker would (``check_packed_batch`` over deserialized
packs — frame tables rebuilt bit-identically by ``ensure_frames``),
and ships only the device verdicts back. Everything judgment-shaped
stays in the runner: native-DFS-sized keys never reach the socket
(checkers/tpu_linearizable.py routes them before packing), and the
runner's ``_finalize`` still runs its CPU diagnostics / overflow-DFS /
fallback ladder on the returned verdicts. A ``_resume`` payload
(device arrays frozen mid-ladder) cannot cross the socket; it is
stripped, and the runner's ``_overflow`` re-runs the spill locally —
PR 5 pinned that the spill verdict is bit-identical at every resume
budget.

Degradation contract: every client failure (no socket, connect
refused, protocol error, service-side exception) returns ``None`` from
``CheckerClient.check`` / ``client_for`` and bumps the
``service.fallback`` counter — the checker then runs the same packs
in-process, so a dead service costs latency, never verdicts.

Wire format (length-prefixed frames, 8-byte little-endian size):

    request:  {"op": "check", "id": n, "sizes": [b0, b1, ...]}\\n
              <pack0 bytes><pack1 bytes>...
    response: {"id": n, "results": [...]}        (or {"id", "error"})
    also:     {"op": "ping"|"stats", "id": n} -> JSON-only responses
"""

from __future__ import annotations

import json
import logging
import os
import queue
import socket
import struct
import tempfile
import threading
import time
from typing import Any, Optional

from . import telemetry
from .telemetry import Telemetry

logger = logging.getLogger("jepsen_etcd_tpu.checker_service")

#: env var naming the service socket; opts/test["checker_service"] wins
ENV_VAR = "JEPSEN_ETCD_TPU_CHECKER_SERVICE"

_LEN = struct.Struct("<Q")

#: refuse frames past this size (a corrupt length prefix must not
#: allocate the heap): 1 GiB >> any real campaign's per-request packs
MAX_FRAME = 1 << 30


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Optional[bytes]:
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (n,) = _LEN.unpack(head)
    if n > MAX_FRAME:
        raise ValueError(f"frame of {n} bytes exceeds MAX_FRAME")
    return _recv_exact(sock, n)


def _plain(x: Any) -> Any:
    """JSON-safe copy of a verdict dict: numpy scalars to python,
    device-array payloads (``_resume``) already stripped by callers."""
    if isinstance(x, dict):
        return {k: _plain(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_plain(v) for v in x]
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    item = getattr(x, "item", None)
    if callable(item):
        return item()
    return repr(x)


class _Request:
    """One pending check request: its packs, arrival time, the
    originating run's trace id, and the connection to answer on."""

    __slots__ = ("conn", "wlock", "req_id", "packs", "t_arrive",
                 "trace")

    def __init__(self, conn, wlock, req_id, packs, t_arrive,
                 trace=None):
        self.conn = conn
        self.wlock = wlock
        self.req_id = req_id
        self.packs = packs
        self.t_arrive = t_arrive
        self.trace = trace


def device_name(d=None) -> str:
    """``platform+id`` of a device (``tpu0``, ``cpu3``) — the per-shard
    attribution key the sharded service carries on every counter. With
    no argument it names the process's default device (device 0), which
    keeps the historical ``tpu0``/``cpu0`` labels stable for existing
    dashboards; ``host0`` when jax is unavailable."""
    if d is None:
        try:
            import jax
            d = jax.devices()[0]
        except Exception:
            return "host0"
    return f"{d.platform}{d.id}"


class DevicePlacement:
    """Sticky round-robin group→device placement.

    The first time a (bucket, width) group shape appears it takes the
    next chip in round-robin order; every later tick reuses that chip,
    so the group's compiled executable stays warm exactly where its
    inputs land (a fresh shape on a fresh chip compiles once — moving
    shapes between chips would recompile per move). All state lives
    under one lock: the service dispatcher, the stats reader, and the
    in-process fallback path (``fallback_device_for``) share instances.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._map: dict = {}
        self._devices: Optional[list] = None
        self._next = 0

    def _ensure(self) -> list:
        # callers hold self._lock
        if self._devices is None:
            try:
                import jax
                self._devices = list(jax.devices())
            except Exception:
                self._devices = []
        return self._devices

    def devices(self) -> list:
        """Every visible device (imports jax on first use)."""
        with self._lock:
            return list(self._ensure())

    def devices_if_known(self) -> list:
        """Like ``devices()`` but never imports jax — empty until some
        assignment forced the device list (safe from stats readers)."""
        with self._lock:
            return list(self._devices or [])

    def assign(self, key) -> tuple:
        """(device index, device) for a group key — sticky round-robin;
        ``(0, None)`` when no device is visible."""
        with self._lock:
            devs = self._ensure()
            if not devs:
                return 0, None
            idx = self._map.get(key)
            if idx is None:
                idx = self._next % len(devs)
                self._next += 1
                self._map[key] = idx
            return idx, devs[idx]

    def snapshot(self) -> dict:
        """JSON-safe ``repr(group_key) -> device name`` map."""
        with self._lock:
            devs = self._devices or []
            return {repr(k): (device_name(devs[i]) if i < len(devs)
                              else f"dev{i}")
                    for k, i in self._map.items()}


#: per-process sticky placement for in-process fallbacks — the same
#: group→device policy the service dispatcher runs, so a service-down
#: fallback lands on the chip a group's executable is (or will be)
#: warm on instead of re-serializing everything onto device 0
_process_placement: Optional[DevicePlacement] = None
_process_placement_lock = threading.Lock()


def process_placement() -> DevicePlacement:
    global _process_placement
    with _process_placement_lock:
        if _process_placement is None:
            _process_placement = DevicePlacement()
        return _process_placement


def fallback_device_for(tel: Optional[Telemetry] = None):
    """A ``group_key -> device`` callback for
    ``wgl.check_packed_batch(device_for=...)``: routes a service-down
    fallback through the process's sticky placement map and counts
    each placed group under ``service.fallback.<dev>``. Returns None
    when fewer than two devices are visible — placement is a no-op
    there, and the historical single-device behavior is already
    correct."""
    place = process_placement()
    if len(place.devices()) < 2:
        return None

    def device_for(key):
        _idx, dev = place.assign(key)
        if tel is not None and dev is not None:
            tel.counter("service.fallback." + device_name(dev))
        return dev

    return device_for


class _GroupJob:
    """One group's device dispatch, run on a per-device worker thread.
    The job owns all its state — the worker only calls ``run()`` and
    the dispatcher only reads after ``done`` is set — so the Event is
    the whole synchronization story."""

    __slots__ = ("packs", "key", "device", "dev_names", "shard",
                 "prepared", "outs", "error", "busy_s", "done")

    def __init__(self, packs, key, device, dev_names, shard, prepared):
        self.packs = packs
        self.key = key
        self.device = device
        self.dev_names = dev_names
        self.shard = shard
        self.prepared = prepared
        self.outs = None
        self.error = None
        self.busy_s = 0.0
        self.done = threading.Event()

    def run(self) -> None:
        from ..ops import wgl
        t0 = time.monotonic()
        try:
            prepared = ({self.key: self.prepared}
                        if self.prepared is not None else None)
            # module-attribute lookup at call time: tests monkeypatch
            # wgl.check_packed_batch and the jobs must see it
            self.outs = wgl.check_packed_batch(
                self.packs, device=self.device, shard=self.shard,
                prepared=prepared)
        except Exception as e:  # degrade, never wedge clients
            logger.exception("checker service group dispatch failed")
            self.error = repr(e)
        finally:
            self.busy_s = time.monotonic() - t0
            self.done.set()


class _Tick:
    """One in-flight coalescing tick: its request batch, flattened
    pack slots, per-pack results, and the group jobs out on the
    per-device worker queues. Exists so the dispatcher can hold tick
    N open (jobs running on their chips) while it packs tick N+1."""

    __slots__ = ("batch", "slots", "results", "jobs", "trivial_err",
                 "t_start", "span", "n_packs", "n_groups", "placement",
                 "sharded", "lanes", "pack_s")


class CheckerService:
    """The device-owning batch scheduler.

    Threads: one acceptor, one reader per connection (they only parse
    and enqueue), ONE dispatcher that freezes batches, packs host
    tables, and places groups, and one worker per visible device that
    runs the placed group dispatches (``_GroupJob.run``). Each chip's
    launches stay serialized on its own worker — concurrent jax calls
    only ever target DIFFERENT devices. All shared service state
    (pending queue, connection list, worker queues, stop flag) is
    mutated under ``_cv`` only; job state is handed off through the
    per-job ``done`` event, and the placement map has its own lock.
    """

    def __init__(self, path: Optional[str] = None,
                 tick_s: float = 0.05,
                 tel: Optional[Telemetry] = None):
        if path is None:
            path = os.path.join(
                tempfile.mkdtemp(prefix="jet-checker-"), "checker.sock")
        self.path = path
        self.tick_s = tick_s
        self.tel = tel if tel is not None else Telemetry()
        self._cv = threading.Condition()
        self._pending: list[_Request] = []
        self._conns: list[socket.socket] = []
        self._threads: list[threading.Thread] = []
        self._stopped = False
        self._listener: Optional[socket.socket] = None
        #: sticky group→device map; lazy so constructing a service
        #: (tests, option plumbing) never imports jax
        self._placement = DevicePlacement()
        self._work_qs: list[queue.Queue] = []

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "CheckerService":
        ls = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            os.unlink(self.path)
        except OSError:
            pass
        ls.bind(self.path)
        ls.listen(64)
        # closing a listener does NOT wake a blocked accept() on
        # Linux; poll with a short timeout so close() never hangs
        ls.settimeout(0.25)
        with self._cv:
            self._listener = ls
            acceptor = threading.Thread(
                target=self._accept_loop, name="checker-svc-accept",
                daemon=True)
            dispatcher = threading.Thread(
                target=self._dispatch_loop, name="checker-svc-dispatch",
                daemon=True)
            self._threads += [acceptor, dispatcher]
        acceptor.start()
        dispatcher.start()
        logger.info("checker service listening on %s", self.path)
        return self

    def close(self) -> None:
        with self._cv:
            if self._stopped:
                return
            self._stopped = True
            self._cv.notify_all()
            ls = self._listener
            conns = list(self._conns)
            threads = list(self._threads)
        if ls is not None:
            try:
                ls.close()
            except OSError:
                pass
        for c in conns:
            # shutdown (not just close) reliably wakes a reader
            # blocked in recv() on this connection
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in threads:
            t.join(timeout=30)
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def stats(self) -> dict:
        """The service's telemetry summary (counters + spans) plus the
        device roster and sticky placement map. Uses the non-forcing
        device peek so a stats RPC from a reader thread never
        initializes jax — empty lists until the first tick ran."""
        out = self.tel.summary()
        out["devices"] = [device_name(d)
                          for d in self._placement.devices_if_known()]
        out["placement"] = self._placement.snapshot()
        return out

    # -- socket side ---------------------------------------------------------
    def _accept_loop(self) -> None:
        while True:
            with self._cv:
                if self._stopped:
                    return
                ls = self._listener
            try:
                conn, _ = ls.accept()
            except socket.timeout:
                continue  # poll the stop flag
            except OSError:
                return  # listener closed by close()
            wlock = threading.Lock()
            reader = threading.Thread(
                target=self._reader, args=(conn, wlock),
                name="checker-svc-reader", daemon=True)
            with self._cv:
                if self._stopped:
                    conn.close()
                    return
                self._conns.append(conn)
                self._threads.append(reader)
            reader.start()

    def _reader(self, conn: socket.socket, wlock: threading.Lock) -> None:
        try:
            while True:
                frame = _recv_frame(conn)
                if frame is None:
                    return
                self._handle(conn, wlock, frame)
        except (OSError, ValueError) as e:
            logger.debug("checker service reader exits: %r", e)
        finally:
            with self._cv:
                if conn in self._conns:
                    self._conns.remove(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, conn, wlock, frame: bytes) -> None:
        from ..ops import wgl
        nl = frame.index(b"\n") if b"\n" in frame else len(frame)
        head = json.loads(frame[:nl].decode())
        op = head.get("op")
        if op == "ping":
            with wlock:
                _send_frame(conn, json.dumps(
                    {"id": head.get("id"), "ok": True}).encode())
            return
        if op == "stats":
            with wlock:
                _send_frame(conn, json.dumps(
                    {"id": head.get("id"),
                     "stats": self.stats()}).encode())
            return
        if op != "check":
            with wlock:
                _send_frame(conn, json.dumps(
                    {"id": head.get("id"),
                     "error": f"unknown op {op!r}"}).encode())
            return
        packs = []
        off = nl + 1
        for size in head["sizes"]:
            packs.append(wgl.deserialize_packed(frame[off:off + size]))
            off += size
        req = _Request(conn, wlock, head.get("id"), packs,
                       time.monotonic(), trace=head.get("trace"))
        self.tel.counter("service.requests")
        self.tel.counter("service.submitted", len(packs))
        with self._cv:
            self._pending.append(req)
            self._cv.notify_all()

    # -- device side ---------------------------------------------------------
    def _dispatch_loop(self) -> None:
        # deep wgl code reaches the recorder via telemetry.current();
        # the thread-local pin cannot race — other threads never see
        # it, and each per-device worker pins its own.
        telemetry.set_thread_current(self.tel)
        inflight: Optional[_Tick] = None
        while True:
            with self._cv:
                while not self._pending and not self._stopped:
                    self._cv.wait()
                if self._stopped and not self._pending:
                    break
            # coalescing window: let concurrently-finishing runs land
            # their submissions before the batch is frozen
            time.sleep(self.tick_s)
            with self._cv:
                batch, self._pending = self._pending, []
            if not batch:
                if inflight is not None:
                    self._finalize_tick(inflight)
                    inflight = None
                continue
            self._ensure_workers()
            # double buffer: pack tick N+1's host tables WHILE tick
            # N's jobs are still running on their chips — pack_s and
            # device wall overlap instead of serialize
            tick = self._prepare_tick(batch)
            if inflight is not None:
                self._finalize_tick(inflight)
                inflight = None
            self._submit_tick(tick)
            with self._cv:
                more = bool(self._pending) and not self._stopped
            if more:
                inflight = tick  # keep packing; finalize next loop
            else:
                self._finalize_tick(tick)  # idle: reply promptly
        if inflight is not None:
            self._finalize_tick(inflight)
        with self._cv:
            qs = list(self._work_qs)
        for q in qs:
            q.put(None)  # worker shutdown sentinels

    def _ensure_workers(self) -> None:
        """Lazily start one worker thread per visible device (first
        batch only — jax is first imported here, on the dispatcher)."""
        with self._cv:
            if self._work_qs:
                return
        n = max(1, len(self._placement.devices()))
        qs = [queue.Queue() for _ in range(n)]
        threads = [threading.Thread(target=self._device_worker,
                                    args=(q,),
                                    name=f"checker-svc-dev{i}",
                                    daemon=True)
                   for i, q in enumerate(qs)]
        with self._cv:
            self._work_qs = qs
            self._threads += threads
        for t in threads:
            t.start()

    def _device_worker(self, q: queue.Queue) -> None:
        telemetry.set_thread_current(self.tel)
        while True:
            job = q.get()
            if job is None:
                return
            job.run()

    def _prepare_tick(self, batch: list[_Request]) -> _Tick:
        """The host half of a tick: flatten the batch, answer trivial
        packs inline, group the rest, place each group on its sticky
        device, and pack the padded host tables (the work that
        overlaps the previous tick's device wall)."""
        from ..ops import wgl
        tick = _Tick()
        tick.batch = batch
        tick.t_start = time.monotonic()
        tick.span = None
        tick.trivial_err = None
        all_packs = []
        slots = []  # (request index, offset into its results)
        for ri, req in enumerate(batch):
            for j, p in enumerate(req.packs):
                all_packs.append(p)
                slots.append((ri, j))
        tick.slots = slots
        tick.n_packs = len(all_packs)
        tick.results = [None] * len(all_packs)
        groups: dict = {}
        trivial = []
        for i, p in enumerate(all_packs):
            if p.ok and p.R > 0:
                groups.setdefault(wgl.group_key(p), []).append(i)
            else:
                trivial.append(i)
        tick.n_groups = len(groups)
        if trivial:
            # degenerate packs (rejected windows, zero reads) never
            # touch a device; answer them on the dispatcher thread
            try:
                for i, out in zip(trivial, wgl.check_packed_batch(
                        [all_packs[i] for i in trivial])):
                    tick.results[i] = out
            except Exception as e:
                logger.exception("checker service trivial check failed")
                tick.trivial_err = repr(e)
        devs = self._placement.devices()
        n_dev = max(1, len(devs))
        # one group and a whole mesh: spread the batch axis of the
        # wave ladder itself instead of parking 7 chips — the key axis
        # pads to the lane count, so a fleet that only ever produces
        # one (bucket, width) shape still exercises (and warms) every
        # chip at one launch per tick, even for a lone pack (wgl picks
        # shard_map for oversized groups, GSPMD scatter for small)
        only = (next(iter(groups.values()))
                if len(groups) == 1 else None)
        tick.sharded = only is not None and n_dev > 1
        tick.lanes = 1
        tick.placement = {}
        tick.jobs = []
        for key, idxs in groups.items():
            gpacks = [all_packs[i] for i in idxs]
            local = list(range(len(gpacks)))
            if tick.sharded:
                lanes = n_dev
                names = [device_name(d) for d in devs[:lanes]]
                prep = wgl.prepare_bucket_group(gpacks, local, key[0],
                                                key[1], lanes=lanes)
                job = _GroupJob(gpacks, key, None, names, True, prep)
                qi = 0
                tick.lanes = lanes
            else:
                qi, dev = self._placement.assign(key)
                names = [device_name(dev) if dev is not None
                         else device_name()]
                prep = None
                if len(idxs) > 1:  # K==1 takes the single-pack path
                    prep = wgl.prepare_bucket_group(gpacks, local,
                                                    key[0], key[1],
                                                    lanes=1)
                job = _GroupJob(gpacks, key, dev, names, False, prep)
            tick.jobs.append((job, idxs, qi))
        tick.pack_s = time.monotonic() - tick.t_start
        return tick

    def _submit_tick(self, tick: _Tick) -> None:
        """Open the tick span and hand every group job to its device's
        worker queue (each chip's launches stay serialized on its own
        worker)."""
        runs = sorted({req.trace for req in tick.batch
                       if req.trace is not None})
        dev_names = sorted({nm for job, _i, _q in tick.jobs
                            for nm in job.dev_names})
        dev_attr = (dev_names[0] if len(dev_names) == 1
                    else f"{len(dev_names)} devices" if dev_names
                    else device_name())
        tick.span = self.tel.span(
            "service.tick", packs=tick.n_packs,
            requests=len(tick.batch), groups=tick.n_groups,
            runs=runs, device=dev_attr, sharded=bool(tick.sharded))
        tick.span.__enter__()
        with self._cv:
            qs = list(self._work_qs)
        for job, _idxs, qi in tick.jobs:
            qs[qi % len(qs)].put(job)

    def _finalize_tick(self, tick: _Tick) -> None:
        """Join the tick's jobs, fold their telemetry (the per-device
        ledger), and answer every request."""
        errors = []
        if tick.trivial_err:
            errors.append(tick.trivial_err)
        busy_by_dev: dict[str, float] = {}
        dispatches: dict[str, int] = {}
        for job, idxs, _qi in tick.jobs:
            job.done.wait(timeout=600)
            if not job.done.is_set():
                errors.append(f"group {job.key!r} dispatch timed out")
                continue
            if job.error is not None:
                errors.append(job.error)
            elif job.outs is not None:
                for i, out in zip(idxs, job.outs):
                    tick.results[i] = out
            # fan-counted: a sharded job burns EVERY lane chip for its
            # wall, a placed job exactly one
            for nm in job.dev_names:
                busy_by_dev[nm] = busy_by_dev.get(nm, 0.0) + job.busy_s
                dispatches[nm] = dispatches.get(nm, 0) + 1
        err = "; ".join(errors) if errors else None
        tick.placement = dict(dispatches)
        tick.span.set(error=err, placement=dict(dispatches))
        tick.span.__exit__(None, None, None)
        # per-device ledger (the shipped==submitted identity of
        # `tel --ledger`, extended per chip): every group this tick
        # dispatched exactly once, plus one extra lane-dispatch per
        # extra chip of the sharded job
        fanout = sum(len(job.dev_names) - 1
                     for job, _i, _q in tick.jobs)
        placed = sum(dispatches.values())
        assert placed == len(tick.jobs) + fanout, \
            (placed, len(tick.jobs), fanout)
        self.tel.counter("service.ticks")
        self.tel.counter("service.group_ticks", tick.n_groups)
        # explicit ledger, not a re-scan: packs in minus one dispatch
        # per group IS the number of device calls coalescing saved
        self.tel.counter("service.coalesced",
                         tick.n_packs - tick.n_groups)
        self.tel.counter("service.batch_occupancy", tick.n_packs,
                         mode="max")
        self.tel.counter("service.pack_s", round(tick.pack_s, 6))
        for nm in sorted(dispatches):
            self.tel.counter("service.device_dispatches." + nm,
                             dispatches[nm])
            self.tel.counter("service.device_busy_s." + nm,
                             round(busy_by_dev[nm], 6))
        if dispatches:
            self.tel.counter("service.device_occupancy",
                             len(dispatches), mode="max")
        if tick.sharded:
            self.tel.counter("service.sharded_ticks")
            self.tel.counter("service.shard_fanout", fanout)
        # each request's wait is rounded ONCE and used everywhere —
        # the summed counter, the hist, and the per-request reply — so
        # per-run attribution re-sums to the service total exactly
        waits = [round(tick.t_start - req.t_arrive, 6)
                 for req in tick.batch]
        self.tel.counter("service.queue_wait_s", round(sum(waits), 6))
        for w in waits:
            self.tel.hist("service.queue_wait_s", w)
        results_by_req: dict[int, list] = {
            ri: [None] * len(req.packs)
            for ri, req in enumerate(tick.batch)}
        if err is None:
            for (ri, j), out in zip(tick.slots, tick.results):
                out = dict(out)
                # frozen-frontier device arrays cannot cross the
                # socket; the runner's overflow path re-runs the spill
                # locally (bit-identical verdict, PR 5 contract)
                out.pop("_resume", None)
                results_by_req[ri][j] = _plain(out)
        for ri, req in enumerate(tick.batch):
            if err is not None:
                payload = {"id": req.req_id, "error": err,
                           "queue_wait_s": waits[ri]}
            else:
                payload = {"id": req.req_id,
                           "results": results_by_req[ri],
                           "queue_wait_s": waits[ri]}
            try:
                with req.wlock:
                    _send_frame(req.conn, json.dumps(payload).encode())
            except OSError:
                logger.debug("checker service: client went away")


# ---------------------------------------------------------------------------
# client side (runs inside runner processes)


class ServiceUnavailable(Exception):
    pass


class CheckerClient:
    """Synchronous client: one request outstanding at a time (the
    checker blocks on its verdicts anyway). Any failure marks the
    client broken; callers fall back to in-process checking."""

    def __init__(self, path: str, timeout: float = 600.0):
        self.path = path
        self.timeout = timeout
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._next_id = 0
        self.broken = False
        #: queue wait the service attributed to the LAST check() reply
        #: (seconds); None until a reply carries one
        self.last_queue_wait_s: Optional[float] = None

    def _rpc(self, head: dict, body: bytes = b"") -> dict:
        with self._lock:
            if self.broken:
                raise ServiceUnavailable(self.path)
            try:
                if self._sock is None:
                    s = socket.socket(socket.AF_UNIX,
                                      socket.SOCK_STREAM)
                    s.settimeout(self.timeout)
                    s.connect(self.path)
                    self._sock = s
                sock = self._sock
                head = dict(head)
                head["id"] = self._next_id
                self._next_id += 1
                _send_frame(sock, json.dumps(head).encode() + b"\n"
                            + body)
                frame = _recv_frame(sock)
                if frame is None:
                    raise ServiceUnavailable("connection closed")
                resp = json.loads(frame.decode())
                if resp.get("id") != head["id"]:
                    raise ServiceUnavailable("response id mismatch")
                return resp
            except (OSError, ValueError, json.JSONDecodeError) as e:
                self.broken = True
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                raise ServiceUnavailable(repr(e)) from e

    def ping(self) -> bool:
        try:
            return bool(self._rpc({"op": "ping"}).get("ok"))
        except ServiceUnavailable:
            return False

    def stats(self) -> Optional[dict]:
        try:
            return self._rpc({"op": "stats"}).get("stats")
        except ServiceUnavailable:
            return None

    def check(self, packs: list,
              trace: Optional[str] = None) -> Optional[list]:
        """Ship packed histories; returns one verdict dict per pack
        (aligned), or None if the service failed — callers MUST then
        check the same packs in-process. ``trace`` is the originating
        run's trace id: the service stamps it on the dispatch tick
        span so the shipped-packs ledger is joinable per run."""
        from ..ops import wgl
        try:
            blobs = [wgl.serialize_packed(p) for p in packs]
            head = {"op": "check", "sizes": [len(b) for b in blobs]}
            if trace is not None:
                head["trace"] = trace
            resp = self._rpc(head, b"".join(blobs))
        except ServiceUnavailable:
            return None
        self.last_queue_wait_s = resp.get("queue_wait_s")
        results = resp.get("results")
        if results is None or len(results) != len(packs):
            # a structured error reply (a failed tick): the transport
            # is healthy, so DON'T latch broken — this call falls back
            # to in-process checking, the next may succeed again
            return None
        return results

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


#: per-process client cache; None latches "tried and broken" so a dead
#: service costs one connect attempt per process, not one per key batch
_clients: dict[str, Optional[CheckerClient]] = {}
_clients_lock = threading.Lock()


def endpoint_for(test: Any) -> Optional[str]:
    """The configured service socket for a test dict (or env), if any."""
    path = None
    if isinstance(test, dict):
        path = test.get("checker_service")
    return path or os.environ.get(ENV_VAR) or None


def client_for(test: Any) -> Optional[CheckerClient]:
    """A working (cached) client for the test's service endpoint, or
    None — absent config, failed connect, or a previously broken
    client all mean "check in-process"."""
    path = endpoint_for(test)
    if not path:
        return None
    with _clients_lock:
        if path in _clients:
            c = _clients[path]
            if c is not None and c.broken:
                _clients[path] = None
                c = None
            return c
    client = CheckerClient(path)
    ok = client.ping()
    with _clients_lock:
        _clients[path] = client if ok else None
    if not ok:
        # callers count service.fallback per degraded check; here just
        # explain the latch once
        logger.warning("checker service unreachable at %s; "
                       "checking in-process", path)
        return None
    return _clients[path]


def reset_clients() -> None:
    """Drop the per-process client cache (tests; spawn workers start
    clean anyway)."""
    with _clients_lock:
        for c in _clients.values():
            if c is not None:
                c.close()
        _clients.clear()
