"""Batched TPU checker service: one process owns every device.

The campaign driver (runner/campaign.py) fans runs over a process
pool; if each run dispatched its own device checks it would pay the
~100 ms synchronized-call floor and ~57 ms/launch fixed cost per RUN
(PERF.md §1). This service is the continuous-batching answer (the
Orca/vLLM scheduler shape from PAPERS.md applied to history checking):
runner processes pack their histories ONCE (ops/wgl.py
serialize_packed, ~32 B/op compact vectors), ship them over a socket,
and the service coalesces everything pending across all connections
into one ``wgl.check_packed_batch`` call per tick — one device
dispatch per (bucket, width) group per tick, no matter how many runs
contributed keys.

Transports (runner/transport.py): a local AF_UNIX socket (the
original single-host shape) and TCP (``tcp://HOST:PORT``) for
multi-host fleets, where generator hosts feed one device-owning
service. TCP connections open with a ``JET-HOST <name>`` preamble
(per-host attribution + net/ proxy sniffing) and authenticate with a
shared-secret token carried on a ``hello`` frame; both transports
enforce the per-message length cap before allocating a byte.

Multi-device dispatch (ISSUE 15): the dispatcher assigns each
(bucket, width) group to a chip with a STICKY round-robin map
(``DevicePlacement`` — a group shape always lands on the chip whose
compiled executable is warm) and hands the per-group launches to
per-device worker threads, so a v5e-8's eight chips run eight group
dispatches concurrently instead of queueing one. A tick whose packs
all share ONE group shape instead shards the batch axis of the wave
ladder over the whole mesh with shard_map (the host + device + sharded
split ops/closure.py proved). Host packing is double-buffered: while
tick N's jobs run on their chips, the dispatcher packs tick N+1's
tables (``wgl.prepare_bucket_group``), so pack_s and dispatch wall
overlap instead of serialize; on TPU the packed inputs are donated to
the launch (PERF.md §6).

Soundness contract: the service runs the exact device-path code the
in-process checker would (``check_packed_batch`` over deserialized
packs — frame tables rebuilt bit-identically by ``ensure_frames``),
and ships only the device verdicts back. Everything judgment-shaped
stays in the runner: native-DFS-sized keys never reach the socket
(checkers/tpu_linearizable.py routes them before packing), and the
runner's ``_finalize`` still runs its CPU diagnostics / overflow-DFS /
fallback ladder on the returned verdicts. A ``_resume`` payload
(device arrays frozen mid-ladder) cannot cross the socket; it is
stripped, and the runner's ``_overflow`` re-runs the spill locally —
PR 5 pinned that the spill verdict is bit-identical at every resume
budget.

Degradation contract: every client failure (no socket, connect
refused, protocol error, auth reject, heartbeat silence, service-side
exception) returns ``None`` from ``CheckerClient.check`` /
``client_for`` and bumps the ``service.fallback`` counter — the
checker then runs the same packs in-process, so a dead service costs
latency, never verdicts. Failures are NOT permanent: the client backs
off under capped exponential delay with jitter and re-probes when the
cooldown expires, so a healed service is automatically re-promoted
(``service.reconnects``) mid-campaign.

Flow control: admission happens at the socket edge, not in the
dispatcher. A ``check`` whose packs would overflow the bounded
pending queue — or whose connection already has its in-flight quota
out — is answered immediately with ``{"busy": true, "retry_after_s"}``
(``service.admission_rejects``) instead of queueing unboundedly; the
client sleeps and retries a bounded number of times before falling
back in-process. While a request IS queued, the service sends
heartbeat frames on its connection so the client can distinguish a
slow tick from a dead service without a blind multi-minute wait.

Wire format (length-prefixed frames, 8-byte little-endian size; TCP
adds the ``JET-HOST <name>\\n`` text preamble before the first frame):

    request:  {"op": "check", "id": n, "sizes": [b0, b1, ...]}\\n
              <pack0 bytes><pack1 bytes>...
    response: {"id": n, "results": [...]}        (or {"id", "error"}
              or {"id", "busy": true, "retry_after_s": s})
    also:     {"op": "hello", "id": n, "token": t, "host": h}
              {"op": "ping"|"stats", "id": n} -> JSON-only responses
    async:    {"heartbeat": k, "pending": p}  (service -> any client
              with in-flight requests; not a reply, no id)
"""

from __future__ import annotations

import json
import logging
import os
import queue
import random
import socket
import tempfile
import threading
import time
import zlib
from typing import Any, Optional

from . import telemetry, transport
from .telemetry import Telemetry
from .transport import MAX_FRAME, FrameReader, send_frame as _send_frame

logger = logging.getLogger("jepsen_etcd_tpu.checker_service")

#: env var naming the service endpoint (unix path or tcp://HOST:PORT);
#: opts/test["checker_service"] wins
ENV_VAR = "JEPSEN_ETCD_TPU_CHECKER_SERVICE"

#: env var carrying the shared-secret auth token;
#: opts/test["checker_service_token"] wins
ENV_TOKEN = "JEPSEN_ETCD_TPU_SERVICE_TOKEN"

#: env var naming this generator host for per-host attribution;
#: opts/test["host_id"] wins
ENV_HOST = "JEPSEN_ETCD_TPU_HOST"

#: client reconnect backoff: capped exponential with jitter. Module
#: level so tests can compress the clock.
RETRY_BASE_S = 0.25
RETRY_CAP_S = 30.0


def _plain(x: Any) -> Any:
    """JSON-safe copy of a verdict dict: numpy scalars to python,
    device-array payloads (``_resume``) already stripped by callers."""
    if isinstance(x, dict):
        return {k: _plain(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_plain(v) for v in x]
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    item = getattr(x, "item", None)
    if callable(item):
        return item()
    return repr(x)


class _Conn:
    """One client connection's server-side state. ``inflight`` is the
    admission-control ledger (requests queued or ticking, not yet
    answered) and the heartbeat trigger; mutated under the service
    ``_cv`` only."""

    __slots__ = ("sock", "wlock", "tcp", "host", "authed", "inflight")

    def __init__(self, sock, tcp=False):
        self.sock = sock
        self.wlock = threading.Lock()
        self.tcp = tcp
        self.host: Optional[str] = None
        self.authed = False
        self.inflight = 0


class _Request:
    """One pending check request: its packs, arrival time, the
    originating run's trace id, and the connection to answer on."""

    __slots__ = ("client", "req_id", "packs", "t_arrive", "trace")

    def __init__(self, client: _Conn, req_id, packs, t_arrive,
                 trace=None):
        self.client = client
        self.req_id = req_id
        self.packs = packs
        self.t_arrive = t_arrive
        self.trace = trace


def device_name(d=None) -> str:
    """``platform+id`` of a device (``tpu0``, ``cpu3``) — the per-shard
    attribution key the sharded service carries on every counter. With
    no argument it names the process's default device (device 0), which
    keeps the historical ``tpu0``/``cpu0`` labels stable for existing
    dashboards; ``host0`` when jax is unavailable."""
    if d is None:
        try:
            import jax
            d = jax.devices()[0]
        except Exception:
            return "host0"
    return f"{d.platform}{d.id}"


class DevicePlacement:
    """Sticky round-robin group→device placement.

    The first time a (bucket, width) group shape appears it takes the
    next chip in round-robin order; every later tick reuses that chip,
    so the group's compiled executable stays warm exactly where its
    inputs land (a fresh shape on a fresh chip compiles once — moving
    shapes between chips would recompile per move). All state lives
    under one lock: the service dispatcher, the stats reader, and the
    in-process fallback path (``fallback_device_for``) share instances.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._map: dict = {}
        self._devices: Optional[list] = None
        self._next = 0

    def _ensure(self) -> list:
        # callers hold self._lock
        if self._devices is None:
            try:
                import jax
                self._devices = list(jax.devices())
            except Exception:
                self._devices = []
        return self._devices

    def devices(self) -> list:
        """Every visible device (imports jax on first use)."""
        with self._lock:
            return list(self._ensure())

    def devices_if_known(self) -> list:
        """Like ``devices()`` but never imports jax — empty until some
        assignment forced the device list (safe from stats readers)."""
        with self._lock:
            return list(self._devices or [])

    def assign(self, key) -> tuple:
        """(device index, device) for a group key — sticky round-robin;
        ``(0, None)`` when no device is visible."""
        with self._lock:
            devs = self._ensure()
            if not devs:
                return 0, None
            idx = self._map.get(key)
            if idx is None:
                idx = self._next % len(devs)
                self._next += 1
                self._map[key] = idx
            return idx, devs[idx]

    def snapshot(self) -> dict:
        """JSON-safe ``repr(group_key) -> device name`` map."""
        with self._lock:
            devs = self._devices or []
            return {repr(k): (device_name(devs[i]) if i < len(devs)
                              else f"dev{i}")
                    for k, i in self._map.items()}


#: per-process sticky placement for in-process fallbacks — the same
#: group→device policy the service dispatcher runs, so a service-down
#: fallback lands on the chip a group's executable is (or will be)
#: warm on instead of re-serializing everything onto device 0
_process_placement: Optional[DevicePlacement] = None
_process_placement_lock = threading.Lock()


def process_placement() -> DevicePlacement:
    global _process_placement
    with _process_placement_lock:
        if _process_placement is None:
            _process_placement = DevicePlacement()
        return _process_placement


def fallback_device_for(tel: Optional[Telemetry] = None):
    """A ``group_key -> device`` callback for
    ``wgl.check_packed_batch(device_for=...)``: routes a service-down
    fallback through the process's sticky placement map and counts
    each placed group under ``service.fallback.<dev>``. Returns None
    when fewer than two devices are visible — placement is a no-op
    there, and the historical single-device behavior is already
    correct."""
    place = process_placement()
    if len(place.devices()) < 2:
        return None

    def device_for(key):
        _idx, dev = place.assign(key)
        if tel is not None and dev is not None:
            tel.counter("service.fallback." + device_name(dev))
        return dev

    return device_for


class _GroupJob:
    """One group's device dispatch, run on a per-device worker thread.
    The job owns all its state — the worker only calls ``run()`` and
    the dispatcher only reads after ``done`` is set — so the Event is
    the whole synchronization story."""

    __slots__ = ("packs", "key", "device", "dev_names", "shard",
                 "prepared", "outs", "error", "busy_s", "done")

    def __init__(self, packs, key, device, dev_names, shard, prepared):
        self.packs = packs
        self.key = key
        self.device = device
        self.dev_names = dev_names
        self.shard = shard
        self.prepared = prepared
        self.outs = None
        self.error = None
        self.busy_s = 0.0
        self.done = threading.Event()

    def run(self) -> None:
        from ..ops import wgl
        t0 = time.monotonic()
        try:
            prepared = ({self.key: self.prepared}
                        if self.prepared is not None else None)
            # module-attribute lookup at call time: tests monkeypatch
            # wgl.check_packed_batch and the jobs must see it
            self.outs = wgl.check_packed_batch(
                self.packs, device=self.device, shard=self.shard,
                prepared=prepared)
        except Exception as e:  # degrade, never wedge clients
            logger.exception("checker service group dispatch failed")
            self.error = repr(e)
        finally:
            self.busy_s = time.monotonic() - t0
            self.done.set()


class _Tick:
    """One in-flight coalescing tick: its request batch, flattened
    pack slots, per-pack results, and the group jobs out on the
    per-device worker queues. Exists so the dispatcher can hold tick
    N open (jobs running on their chips) while it packs tick N+1."""

    __slots__ = ("batch", "slots", "results", "jobs", "trivial_err",
                 "t_start", "span", "n_packs", "n_groups", "placement",
                 "sharded", "lanes", "pack_s")


class CheckerService:
    """The device-owning batch scheduler.

    Threads: one acceptor per listener (unix always, TCP when
    enabled), one reader per connection (they only parse, admit, and
    enqueue), ONE dispatcher that freezes batches, packs host tables,
    and places groups, one worker per visible device that runs the
    placed group dispatches (``_GroupJob.run``), and one heartbeat
    sender. Each chip's launches stay serialized on its own worker —
    concurrent jax calls only ever target DIFFERENT devices. All
    shared service state (pending queue, admission ledgers, connection
    list, worker queues, stop flag) is mutated under ``_cv`` only; job
    state is handed off through the per-job ``done`` event, and the
    placement map has its own lock.
    """

    def __init__(self, path: Optional[str] = None,
                 tick_s: float = 0.05,
                 tel: Optional[Telemetry] = None,
                 tcp=None,
                 auth_token: Optional[str] = None,
                 max_pending_packs: int = 512,
                 max_inflight_per_conn: int = 8,
                 heartbeat_s: float = 1.0,
                 max_frame: int = MAX_FRAME,
                 shutdown_join_s: float = 30.0):
        if path is None:
            path = os.path.join(
                tempfile.mkdtemp(prefix="jet-checker-"), "checker.sock")
        self.path = path
        self.tick_s = tick_s
        self.tel = tel if tel is not None else Telemetry()
        #: TCP listen spec: None/False -> unix only; True -> loopback
        #: ephemeral port; int port or "HOST:PORT" -> explicit bind
        self.tcp = tcp
        self.tcp_endpoint: Optional[str] = None
        self.auth_token = (auth_token if auth_token is not None
                           else os.environ.get(ENV_TOKEN) or None)
        self.max_pending_packs = max_pending_packs
        self.max_inflight_per_conn = max_inflight_per_conn
        self.heartbeat_s = heartbeat_s
        self.max_frame = max_frame
        self.shutdown_join_s = shutdown_join_s
        #: threads still alive after close() gave up joining them —
        #: surfaced in stats() and the service.shutdown_leaked_threads
        #: counter so a wedged worker is a ledger entry, not a mystery
        self.shutdown_leaked_threads = 0
        self._cv = threading.Condition()
        self._pending: list[_Request] = []
        self._pending_packs = 0  # admission ledger: queued + ticking
        self._conns: list[_Conn] = []
        self._threads: list[threading.Thread] = []
        self._stopped = False
        self._listener: Optional[socket.socket] = None
        self._tcp_listener: Optional[socket.socket] = None
        #: sticky group→device map; lazy so constructing a service
        #: (tests, option plumbing) never imports jax
        self._placement = DevicePlacement()
        self._work_qs: list[queue.Queue] = []

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "CheckerService":
        ls = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            os.unlink(self.path)
        except OSError:
            pass
        ls.bind(self.path)
        ls.listen(64)
        # closing a listener does NOT wake a blocked accept() on
        # Linux; poll with a short timeout so close() never hangs
        ls.settimeout(0.25)
        ts = None
        if self.tcp:
            ts, self.tcp_endpoint = transport.listen_tcp(self.tcp)
            ts.settimeout(0.25)
        with self._cv:
            self._listener = ls
            self._tcp_listener = ts
            threads = [
                threading.Thread(
                    target=self._accept_loop, args=(ls, False),
                    name="checker-svc-accept", daemon=True),
                threading.Thread(
                    target=self._dispatch_loop,
                    name="checker-svc-dispatch", daemon=True),
                threading.Thread(
                    target=self._heartbeat_loop,
                    name="checker-svc-heartbeat", daemon=True),
            ]
            if ts is not None:
                threads.append(threading.Thread(
                    target=self._accept_loop, args=(ts, True),
                    name="checker-svc-accept-tcp", daemon=True))
            self._threads += threads
        for t in threads:
            t.start()
        logger.info("checker service listening on %s%s", self.path,
                    f" and {self.tcp_endpoint}" if ts is not None
                    else "")
        return self

    def close(self) -> None:
        with self._cv:
            if self._stopped:
                return
            self._stopped = True
            self._cv.notify_all()
            listeners = [self._listener, self._tcp_listener]
            conns = list(self._conns)
            threads = list(self._threads)
        for ls in listeners:
            if ls is not None:
                try:
                    ls.close()
                except OSError:
                    pass
        for c in conns:
            # shutdown (not just close) reliably wakes a reader
            # blocked in recv() on this connection
            try:
                c.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.sock.close()
            except OSError:
                pass
        for t in threads:
            t.join(timeout=self.shutdown_join_s)
        leaked = [t.name for t in threads if t.is_alive()]
        if leaked:
            # a thread that outlived its join grace is leaked, not
            # merely slow: say so and put it on the ledger instead of
            # silently discarding the join result
            logger.warning(
                "checker service shutdown leaked %d thread(s): %s",
                len(leaked), ", ".join(sorted(leaked)))
            self.tel.counter("service.shutdown_leaked_threads",
                             len(leaked))
        self.shutdown_leaked_threads = len(leaked)
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def stats(self) -> dict:
        """The service's telemetry summary (counters + spans) plus the
        device roster, sticky placement map, and transport endpoints.
        Uses the non-forcing device peek so a stats RPC from a reader
        thread never initializes jax — empty lists until the first
        tick ran."""
        out = self.tel.summary()
        out["devices"] = [device_name(d)
                          for d in self._placement.devices_if_known()]
        out["placement"] = self._placement.snapshot()
        out["endpoint"] = self.path
        out["tcp_endpoint"] = self.tcp_endpoint
        out["shutdown_leaked_threads"] = self.shutdown_leaked_threads
        return out

    # -- socket side ---------------------------------------------------------
    def _accept_loop(self, ls: socket.socket, tcp: bool) -> None:
        while True:
            with self._cv:
                if self._stopped:
                    return
            try:
                conn, _ = ls.accept()
            except socket.timeout:
                continue  # poll the stop flag
            except OSError:
                return  # listener closed by close()
            cstate = _Conn(conn, tcp=tcp)
            reader = threading.Thread(
                target=self._reader, args=(cstate,),
                name="checker-svc-reader", daemon=True)
            with self._cv:
                if self._stopped:
                    conn.close()
                    return
                self._conns.append(cstate)
                self._threads.append(reader)
            reader.start()

    def _reader(self, cstate: _Conn) -> None:
        try:
            reader = FrameReader(cstate.sock, max_frame=self.max_frame)
            if cstate.tcp:
                # TCP opens with "JET-HOST <name>\n" — the same line
                # the net/ proxy sniffs for fault attribution; absent
                # (a bare frame) the connection is simply anonymous
                host = reader.read_preamble()
                if host:
                    with self._cv:
                        cstate.host = host
            while True:
                frame = reader.recv_frame()
                if frame is None:
                    return
                self._handle(cstate, frame)
        except (OSError, ValueError) as e:
            logger.debug("checker service reader exits: %r", e)
        finally:
            with self._cv:
                if cstate in self._conns:
                    self._conns.remove(cstate)
            try:
                cstate.sock.close()
            except OSError:
                pass

    def _reply(self, cstate: _Conn, payload: dict) -> None:
        with cstate.wlock:
            _send_frame(cstate.sock, json.dumps(payload).encode())

    def _handle(self, cstate: _Conn, frame: bytes) -> None:
        from ..ops import wgl
        nl = frame.index(b"\n") if b"\n" in frame else len(frame)
        head = json.loads(frame[:nl].decode())
        op = head.get("op")
        rid = head.get("id")
        if op == "hello":
            if self.auth_token and head.get("token") != self.auth_token:
                self.tel.counter("service.auth_rejects")
                self._reply(cstate, {"id": rid,
                                     "error": "bad auth token"})
                raise ValueError("auth token rejected")
            with self._cv:
                cstate.authed = True
                if head.get("host"):
                    cstate.host = head["host"]
            self._reply(cstate, {"id": rid, "ok": True})
            return
        if op == "ping":
            self._reply(cstate, {"id": rid, "ok": True})
            return
        if self.auth_token and not cstate.authed:
            # ping stays open as an unauthenticated liveness probe;
            # everything that reads or submits state requires hello
            self.tel.counter("service.auth_rejects")
            self._reply(cstate, {"id": rid, "error": "auth required"})
            raise ValueError("unauthenticated request")
        if op == "stats":
            self._reply(cstate, {"id": rid, "stats": self.stats()})
            return
        if op != "check":
            self._reply(cstate, {"id": rid,
                                 "error": f"unknown op {op!r}"})
            return
        sizes = head["sizes"]
        n = len(sizes)
        # admission BEFORE deserialization: an over-capacity request
        # costs a JSON head parse and one small reply, never a pack
        # decode or an unbounded queue slot
        with self._cv:
            over = (cstate.inflight >= self.max_inflight_per_conn
                    or self._pending_packs + n > self.max_pending_packs)
            if not over:
                cstate.inflight += 1
                self._pending_packs += n
        if over:
            self.tel.counter("service.admission_rejects")
            self._reply(cstate, {
                "id": rid, "busy": True,
                "retry_after_s": round(max(2 * self.tick_s, 0.05), 3)})
            return
        try:
            packs = []
            off = nl + 1
            for size in sizes:
                packs.append(
                    wgl.deserialize_packed(frame[off:off + size]))
                off += size
        except Exception as e:
            # a malformed pack (wrong wire version mid-stream, torn
            # blob) degrades THIS request, not the connection: refund
            # the admission slots and answer with a structured error
            with self._cv:
                cstate.inflight -= 1
                self._pending_packs -= n
            self.tel.counter("service.bad_requests")
            logger.warning("checker service rejected request: %r", e)
            self._reply(cstate, {"id": rid, "error": repr(e)})
            return
        req = _Request(cstate, rid, packs, time.monotonic(),
                       trace=head.get("trace"))
        self.tel.counter("service.requests")
        self.tel.counter("service.submitted", len(packs))
        if cstate.host:
            self.tel.counter("service.host_submitted." + cstate.host,
                             len(packs))
        with self._cv:
            self._pending.append(req)
            self._cv.notify_all()

    def _heartbeat_loop(self) -> None:
        """Periodically beat every connection with in-flight requests:
        a queued client hears ``{"heartbeat": k, "pending": p}`` once
        per interval, so silence longer than its idle timeout means
        the service is DEAD, not slow — no blind 600 s waits."""
        seq = 0
        while True:
            with self._cv:
                if self._stopped:
                    return
                self._cv.wait(timeout=self.heartbeat_s)
                if self._stopped:
                    return
                targets = [c for c in self._conns if c.inflight > 0]
                pending = self._pending_packs
            if not targets:
                continue
            seq += 1
            payload = json.dumps({"heartbeat": seq,
                                  "pending": pending}).encode()
            sent = 0
            for c in targets:
                try:
                    with c.wlock:
                        _send_frame(c.sock, payload)
                    sent += 1
                except OSError:
                    continue  # reader notices the dead conn
            if sent:
                self.tel.counter("service.heartbeats_sent", sent)

    # -- device side ---------------------------------------------------------
    def _dispatch_loop(self) -> None:
        # deep wgl code reaches the recorder via telemetry.current();
        # the thread-local pin cannot race — other threads never see
        # it, and each per-device worker pins its own.
        telemetry.set_thread_current(self.tel)
        inflight: Optional[_Tick] = None
        while True:
            with self._cv:
                while not self._pending and not self._stopped:
                    self._cv.wait()
                if self._stopped and not self._pending:
                    break
            # coalescing window: let concurrently-finishing runs land
            # their submissions before the batch is frozen
            time.sleep(self.tick_s)
            with self._cv:
                batch, self._pending = self._pending, []
            if not batch:
                if inflight is not None:
                    self._finalize_tick(inflight)
                    inflight = None
                continue
            self._ensure_workers()
            # double buffer: pack tick N+1's host tables WHILE tick
            # N's jobs are still running on their chips — pack_s and
            # device wall overlap instead of serialize
            tick = self._prepare_tick(batch)
            if inflight is not None:
                self._finalize_tick(inflight)
                inflight = None
            self._submit_tick(tick)
            with self._cv:
                more = bool(self._pending) and not self._stopped
            if more:
                inflight = tick  # keep packing; finalize next loop
            else:
                self._finalize_tick(tick)  # idle: reply promptly
        if inflight is not None:
            self._finalize_tick(inflight)
        with self._cv:
            qs = list(self._work_qs)
        for q in qs:
            q.put(None)  # worker shutdown sentinels

    def _ensure_workers(self) -> None:
        """Lazily start one worker thread per visible device (first
        batch only — jax is first imported here, on the dispatcher)."""
        with self._cv:
            if self._work_qs:
                return
        n = max(1, len(self._placement.devices()))
        qs = [queue.Queue() for _ in range(n)]
        threads = [threading.Thread(target=self._device_worker,
                                    args=(q,),
                                    name=f"checker-svc-dev{i}",
                                    daemon=True)
                   for i, q in enumerate(qs)]
        with self._cv:
            self._work_qs = qs
            self._threads += threads
        for t in threads:
            t.start()

    def _device_worker(self, q: queue.Queue) -> None:
        telemetry.set_thread_current(self.tel)
        while True:
            job = q.get()
            if job is None:
                return
            job.run()

    def _prepare_tick(self, batch: list[_Request]) -> _Tick:
        """The host half of a tick: flatten the batch, answer trivial
        packs inline, group the rest, place each group on its sticky
        device, and pack the padded host tables (the work that
        overlaps the previous tick's device wall)."""
        from ..ops import wgl
        tick = _Tick()
        tick.batch = batch
        tick.t_start = time.monotonic()
        tick.span = None
        tick.trivial_err = None
        all_packs = []
        slots = []  # (request index, offset into its results)
        for ri, req in enumerate(batch):
            for j, p in enumerate(req.packs):
                all_packs.append(p)
                slots.append((ri, j))
        tick.slots = slots
        tick.n_packs = len(all_packs)
        tick.results = [None] * len(all_packs)
        groups: dict = {}
        trivial = []
        for i, p in enumerate(all_packs):
            if p.ok and p.R > 0:
                groups.setdefault(wgl.group_key(p), []).append(i)
            else:
                trivial.append(i)
        tick.n_groups = len(groups)
        if trivial:
            # degenerate packs (rejected windows, zero reads) never
            # touch a device; answer them on the dispatcher thread
            try:
                for i, out in zip(trivial, wgl.check_packed_batch(
                        [all_packs[i] for i in trivial])):
                    tick.results[i] = out
            except Exception as e:
                logger.exception("checker service trivial check failed")
                tick.trivial_err = repr(e)
        devs = self._placement.devices()
        n_dev = max(1, len(devs))
        # one group and a whole mesh: spread the batch axis of the
        # wave ladder itself instead of parking 7 chips — the key axis
        # pads to the lane count, so a fleet that only ever produces
        # one (bucket, width) shape still exercises (and warms) every
        # chip at one launch per tick, even for a lone pack (wgl picks
        # shard_map for oversized groups, GSPMD scatter for small)
        only = (next(iter(groups.values()))
                if len(groups) == 1 else None)
        tick.sharded = only is not None and n_dev > 1
        tick.lanes = 1
        tick.placement = {}
        tick.jobs = []
        for key, idxs in groups.items():
            gpacks = [all_packs[i] for i in idxs]
            local = list(range(len(gpacks)))
            if tick.sharded:
                lanes = n_dev
                names = [device_name(d) for d in devs[:lanes]]
                prep = wgl.prepare_bucket_group(gpacks, local, key[0],
                                                key[1], lanes=lanes)
                job = _GroupJob(gpacks, key, None, names, True, prep)
                qi = 0
                tick.lanes = lanes
            else:
                qi, dev = self._placement.assign(key)
                names = [device_name(dev) if dev is not None
                         else device_name()]
                prep = None
                if len(idxs) > 1:  # K==1 takes the single-pack path
                    prep = wgl.prepare_bucket_group(gpacks, local,
                                                    key[0], key[1],
                                                    lanes=1)
                job = _GroupJob(gpacks, key, dev, names, False, prep)
            tick.jobs.append((job, idxs, qi))
        tick.pack_s = time.monotonic() - tick.t_start
        return tick

    def _submit_tick(self, tick: _Tick) -> None:
        """Open the tick span and hand every group job to its device's
        worker queue (each chip's launches stay serialized on its own
        worker)."""
        runs = sorted({req.trace for req in tick.batch
                       if req.trace is not None})
        hosts = sorted({req.client.host for req in tick.batch
                        if req.client.host is not None})
        dev_names = sorted({nm for job, _i, _q in tick.jobs
                            for nm in job.dev_names})
        dev_attr = (dev_names[0] if len(dev_names) == 1
                    else f"{len(dev_names)} devices" if dev_names
                    else device_name())
        tick.span = self.tel.span(
            "service.tick", packs=tick.n_packs,
            requests=len(tick.batch), groups=tick.n_groups,
            runs=runs, hosts=hosts, device=dev_attr,
            sharded=bool(tick.sharded))
        tick.span.__enter__()
        with self._cv:
            qs = list(self._work_qs)
        for job, _idxs, qi in tick.jobs:
            qs[qi % len(qs)].put(job)

    def _finalize_tick(self, tick: _Tick) -> None:
        """Join the tick's jobs, fold their telemetry (the per-device
        ledger), and answer every request."""
        errors = []
        if tick.trivial_err:
            errors.append(tick.trivial_err)
        busy_by_dev: dict[str, float] = {}
        dispatches: dict[str, int] = {}
        for job, idxs, _qi in tick.jobs:
            job.done.wait(timeout=600)
            if not job.done.is_set():
                errors.append(f"group {job.key!r} dispatch timed out")
                continue
            if job.error is not None:
                errors.append(job.error)
            elif job.outs is not None:
                for i, out in zip(idxs, job.outs):
                    tick.results[i] = out
            # fan-counted: a sharded job burns EVERY lane chip for its
            # wall, a placed job exactly one
            for nm in job.dev_names:
                busy_by_dev[nm] = busy_by_dev.get(nm, 0.0) + job.busy_s
                dispatches[nm] = dispatches.get(nm, 0) + 1
        err = "; ".join(errors) if errors else None
        tick.placement = dict(dispatches)
        tick.span.set(error=err, placement=dict(dispatches))
        tick.span.__exit__(None, None, None)
        # per-device ledger (the shipped==submitted identity of
        # `tel --ledger`, extended per chip): every group this tick
        # dispatched exactly once, plus one extra lane-dispatch per
        # extra chip of the sharded job
        fanout = sum(len(job.dev_names) - 1
                     for job, _i, _q in tick.jobs)
        placed = sum(dispatches.values())
        assert placed == len(tick.jobs) + fanout, \
            (placed, len(tick.jobs), fanout)
        self.tel.counter("service.ticks")
        self.tel.counter("service.group_ticks", tick.n_groups)
        # explicit ledger, not a re-scan: packs in minus one dispatch
        # per group IS the number of device calls coalescing saved
        self.tel.counter("service.coalesced",
                         tick.n_packs - tick.n_groups)
        self.tel.counter("service.batch_occupancy", tick.n_packs,
                         mode="max")
        self.tel.counter("service.pack_s", round(tick.pack_s, 6))
        for nm in sorted(dispatches):
            self.tel.counter("service.device_dispatches." + nm,
                             dispatches[nm])
            self.tel.counter("service.device_busy_s." + nm,
                             round(busy_by_dev[nm], 6))
        if dispatches:
            self.tel.counter("service.device_occupancy",
                             len(dispatches), mode="max")
        if tick.sharded:
            self.tel.counter("service.sharded_ticks")
            self.tel.counter("service.shard_fanout", fanout)
        # each request's wait is rounded ONCE and used everywhere —
        # the summed counter, the hist, and the per-request reply — so
        # per-run attribution re-sums to the service total exactly
        waits = [round(tick.t_start - req.t_arrive, 6)
                 for req in tick.batch]
        self.tel.counter("service.queue_wait_s", round(sum(waits), 6))
        for w in waits:
            self.tel.hist("service.queue_wait_s", w)
        results_by_req: dict[int, list] = {
            ri: [None] * len(req.packs)
            for ri, req in enumerate(tick.batch)}
        if err is None:
            for (ri, j), out in zip(tick.slots, tick.results):
                out = dict(out)
                # frozen-frontier device arrays cannot cross the
                # socket; the runner's overflow path re-runs the spill
                # locally (bit-identical verdict, PR 5 contract)
                out.pop("_resume", None)
                results_by_req[ri][j] = _plain(out)
        for ri, req in enumerate(tick.batch):
            if err is not None:
                payload = {"id": req.req_id, "error": err,
                           "queue_wait_s": waits[ri]}
            else:
                payload = {"id": req.req_id,
                           "results": results_by_req[ri],
                           "queue_wait_s": waits[ri]}
            try:
                self._reply(req.client, payload)
            except OSError:
                logger.debug("checker service: client went away")
            finally:
                # refund the admission slots whether or not the client
                # lived to hear the answer — the ledger must drain
                with self._cv:
                    req.client.inflight -= 1
                    self._pending_packs -= len(req.packs)


# ---------------------------------------------------------------------------
# client side (runs inside runner processes)


class ServiceUnavailable(Exception):
    pass


class CheckerClient:
    """Synchronous client: one request outstanding at a time (the
    checker blocks on its verdicts anyway).

    Failures are never permanent. A transport failure closes the
    socket and arms a cooldown (capped exponential backoff + jitter);
    calls during the cooldown raise :class:`ServiceUnavailable`
    immediately (the caller falls back in-process for THAT call), and
    the first call after it expires re-connects — counting
    ``service.reconnects`` when it succeeds, so a healed service is
    re-promoted automatically. While waiting for a verdict the client
    only tolerates ``idle_timeout`` seconds of SILENCE: the service
    heartbeats queued connections every second, so silence means dead,
    not slow — the old blind 600 s wait survives only as the overall
    ``timeout`` ceiling.
    """

    def __init__(self, endpoint: str, timeout: float = 600.0,
                 token: Optional[str] = None,
                 host: Optional[str] = None,
                 connect_timeout: float = 5.0,
                 idle_timeout: float = 20.0,
                 max_busy_retries: int = 4):
        self.endpoint = endpoint
        #: legacy alias (the client predates TCP endpoints)
        self.path = endpoint
        self.timeout = timeout
        self.token = (token if token is not None
                      else os.environ.get(ENV_TOKEN) or None)
        if host is None and transport.is_tcp(endpoint):
            host = (os.environ.get(ENV_HOST)
                    or socket.gethostname() or "client")
        self.host = host
        self.connect_timeout = connect_timeout
        self.idle_timeout = idle_timeout
        self.max_busy_retries = max_busy_retries
        # reentrant: the helpers re-take it around their own state
        # writes even though _rpc already holds it
        self._lock = threading.RLock()
        self._sock: Optional[socket.socket] = None
        self._reader: Optional[FrameReader] = None
        self._next_id = 0
        self._fails = 0
        self._retry_at = 0.0
        # deterministic jitter per endpoint: no two clients of one
        # campaign re-probe a healing service in lockstep
        self._rng = random.Random(zlib.crc32(endpoint.encode()))
        #: queue wait the service attributed to the LAST check() reply
        #: (seconds); None until a reply carries one
        self.last_queue_wait_s: Optional[float] = None

    # -- health --------------------------------------------------------------
    @property
    def broken(self) -> bool:
        """True while the reconnect cooldown is armed (the old
        permanent latch, now with an expiry date)."""
        return self._fails > 0 and time.monotonic() < self._retry_at

    def available(self) -> bool:
        return not self.broken

    @property
    def connected(self) -> bool:
        return self._sock is not None

    @property
    def fails(self) -> int:
        return self._fails

    # -- transport -----------------------------------------------------------
    def _mark_failed_locked(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
                self._reader = None
            self._fails += 1
            delay = min(RETRY_CAP_S,
                        RETRY_BASE_S * (2 ** min(self._fails - 1, 16)))
            delay *= 0.5 + self._rng.random()  # jitter in [0.5x, 1.5x)
            self._retry_at = time.monotonic() + delay

    def _exchange_locked(self, head: dict, body: bytes = b"") -> dict:
        head = dict(head)
        with self._lock:
            head["id"] = self._next_id
            self._next_id += 1
        _send_frame(self._sock, json.dumps(head).encode() + b"\n"
                    + body)
        deadline = time.monotonic() + self.timeout
        while True:
            # FrameReader is re-entrant across socket timeouts, but an
            # idle timeout here means NO bytes — not even a heartbeat
            # — for idle_timeout seconds: the service is dead or cut
            frame = self._reader.recv_frame()
            if frame is None:
                raise ConnectionError("connection closed by service")
            resp = json.loads(frame.decode())
            if "heartbeat" in resp:
                telemetry.current().counter("service.heartbeats_seen")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no reply within timeout={self.timeout}s "
                        "(service alive but stuck)")
                continue
            if resp.get("id") != head["id"]:
                continue  # stale reply from an abandoned exchange
            return resp

    def _connect_locked(self) -> None:
        if self._sock is not None:
            return
        s = transport.connect(self.endpoint,
                              timeout=self.connect_timeout)
        s.settimeout(self.idle_timeout)
        if transport.is_tcp(self.endpoint):
            transport.send_preamble(s, self.host or "client")
        with self._lock:
            self._sock = s
            self._reader = FrameReader(s)
        hello = {"op": "hello"}
        if self.token is not None:
            hello["token"] = self.token
        if self.host is not None:
            hello["host"] = self.host
        resp = self._exchange_locked(hello)
        if resp.get("error"):
            raise ConnectionError(f"hello rejected: {resp['error']}")
        with self._lock:
            if self._fails:
                telemetry.current().counter("service.reconnects")
            self._fails = 0
            self._retry_at = 0.0

    def _rpc(self, head: dict, body: bytes = b"") -> dict:
        with self._lock:
            now = time.monotonic()
            if self._fails and now < self._retry_at:
                raise ServiceUnavailable(
                    f"{self.endpoint}: cooling down "
                    f"{self._retry_at - now:.2f}s after "
                    f"{self._fails} failure(s)")
            try:
                self._connect_locked()
                return self._exchange_locked(head, body)
            except (OSError, ValueError, json.JSONDecodeError) as e:
                self._mark_failed_locked()
                raise ServiceUnavailable(repr(e)) from e

    # -- API -----------------------------------------------------------------
    def ping(self) -> bool:
        try:
            return bool(self._rpc({"op": "ping"}).get("ok"))
        except ServiceUnavailable:
            return False

    def stats(self) -> Optional[dict]:
        try:
            return self._rpc({"op": "stats"}).get("stats")
        except ServiceUnavailable:
            return None

    def check(self, packs: list,
              trace: Optional[str] = None) -> Optional[list]:
        """Ship packed histories; returns one verdict dict per pack
        (aligned), or None if the service failed or stayed saturated —
        callers MUST then check the same packs in-process. ``trace``
        is the originating run's trace id: the service stamps it on
        the dispatch tick span so the shipped-packs ledger is joinable
        per run. A BUSY reply (admission control) is retried under a
        short bounded backoff — the transport is healthy, so it never
        arms the reconnect cooldown."""
        from ..ops import wgl
        blobs = [wgl.serialize_packed(p) for p in packs]
        head = {"op": "check", "sizes": [len(b) for b in blobs]}
        if trace is not None:
            head["trace"] = trace
        body = b"".join(blobs)
        for attempt in range(self.max_busy_retries + 1):
            try:
                resp = self._rpc(head, body)
            except ServiceUnavailable:
                return None
            if resp.get("busy"):
                telemetry.current().counter("service.busy_retries")
                if attempt == self.max_busy_retries:
                    return None  # saturated: fall back in-process
                wait = float(resp.get("retry_after_s") or 0.05)
                time.sleep(min(wait * (attempt + 1), 2.0))
                continue
            break
        with self._lock:
            self.last_queue_wait_s = resp.get("queue_wait_s")
        results = resp.get("results")
        if results is None or len(results) != len(packs):
            # a structured error reply (a failed tick): the transport
            # is healthy, so no cooldown — this call falls back to
            # in-process checking, the next may succeed again
            return None
        return results

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
                self._reader = None


#: per-process client cache. Entries are kept across failures — the
#: client's own backoff cooldown IS the negative cache, and it
#: expires, so a healed service gets re-probed instead of being
#: latched dead for the life of the process.
_clients: dict[str, CheckerClient] = {}
_clients_lock = threading.Lock()


def endpoint_for(test: Any) -> Optional[str]:
    """The configured service endpoint (unix path or tcp://HOST:PORT)
    for a test dict (or env), if any."""
    ep = None
    if isinstance(test, dict):
        ep = test.get("checker_service")
    return ep or os.environ.get(ENV_VAR) or None


def token_for(test: Any) -> Optional[str]:
    tok = None
    if isinstance(test, dict):
        tok = test.get("checker_service_token")
    return tok or os.environ.get(ENV_TOKEN) or None


def host_for(test: Any) -> Optional[str]:
    host = None
    if isinstance(test, dict):
        host = test.get("host_id")
    return host or os.environ.get(ENV_HOST) or None


def client_for(test: Any) -> Optional[CheckerClient]:
    """A working (cached) client for the test's service endpoint, or
    None — absent config, failed connect, or a client inside its
    reconnect cooldown all mean "check in-process THIS call". Unlike
    the old permanent latch, a dead endpoint is re-probed once per
    backoff window, so a service that comes up mid-campaign is
    adopted automatically."""
    endpoint = endpoint_for(test)
    if not endpoint:
        return None
    with _clients_lock:
        client = _clients.get(endpoint)
        if client is None:
            client = CheckerClient(endpoint, token=token_for(test),
                                   host=host_for(test))
            _clients[endpoint] = client
    if client.connected:
        return client
    if not client.available():
        return None  # cooling down; the entry expires on its own
    if client.ping():
        return client
    log = logger.warning if client.fails == 1 else logger.debug
    log("checker service unreachable at %s; checking in-process "
        "(retry in <=%.1fs)", endpoint,
        max(0.0, client._retry_at - time.monotonic()))
    return None


def reset_clients() -> None:
    """Drop the per-process client cache (tests; spawn workers start
    clean anyway)."""
    with _clients_lock:
        for c in _clients.values():
            c.close()
        _clients.clear()
