"""A wall-clock loop with the SimLoop API: the bridge to real SUTs.

The simulated stack runs on virtual time (runner/sim.py). Driving a
*real* etcd (client/etcd_http.py) needs real time and real I/O, but the
interpreter, generators, and clients only speak the narrow SimLoop
surface (``now``/``spawn``/``call_later``/``sleep``/``rng``) — so a
wall-clock implementation of that same surface lets the whole harness
run unchanged against a live cluster, the way the reference harness
drives its cluster over wall-clock JVM threads (README:3-4).

Blocking I/O (HTTP requests to etcd's gRPC gateway) runs on a thread
pool via ``run_in_thread``; completions re-enter the loop through
``call_soon_threadsafe``. Timers fire when the monotonic clock passes
them. Determinism is intentionally NOT promised here — that is the sim
loop's job; this loop exists so the same tests can also run against
reality.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional

from .sim import Future, SimLoop


class WallLoop(SimLoop):
    def __init__(self, seed: int = 0, pool_size: int = 32):
        super().__init__(seed=seed)
        self._cond = threading.Condition()
        self._external: deque = deque()
        self._t0 = time.monotonic_ns()
        self._pool = ThreadPoolExecutor(max_workers=pool_size)
        self._in_flight = 0  # pool submissions whose callback hasn't run

    def _wall(self) -> int:
        return time.monotonic_ns() - self._t0

    # -- cross-thread entry points ------------------------------------------

    def call_soon_threadsafe(self, cb: Callable, *args: Any) -> None:
        with self._cond:
            self._external.append((cb, args))
            self._cond.notify()

    def run_in_thread(self, fn: Callable, *args: Any,
                      **kwargs: Any) -> Future:
        """Run blocking fn on the pool; resolve a loop Future with its
        result (exceptions propagate)."""
        fut = self.future()
        with self._cond:
            self._in_flight += 1

        def _finish(resolve, value):
            with self._cond:
                self._in_flight -= 1
            resolve(value)

        def work():
            try:
                r = fn(*args, **kwargs)
            except BaseException as e:
                self.call_soon_threadsafe(_finish, fut.set_exception, e)
            else:
                self.call_soon_threadsafe(_finish, fut.set_result, r)

        self._pool.submit(work)
        return fut

    # -- the loop ------------------------------------------------------------

    def run(self, until: Optional[Future] = None,
            max_time: Optional[int] = None) -> Any:
        while True:
            # externals first (I/O completions)
            while True:
                with self._cond:
                    if not self._external:
                        break
                    cb, args = self._external.popleft()
                self.now = self._wall()
                cb(*args)
            # due timers
            while self._heap and self._heap[0][0] <= self._wall():
                entry = heapq.heappop(self._heap)
                t, _, cb, args = entry
                if cb is None:
                    self._dead -= 1
                    continue  # cancelled
                self.now = max(self._wall(), t)
                cb(*args)
            if until is not None and until.done:
                return until.result()
            if max_time is not None and self._wall() >= max_time:
                self.now = self._wall()
                return None
            with self._cond:
                if self._external:
                    continue
                while self._heap and self._heap[0][2] is None:
                    heapq.heappop(self._heap)  # drop cancelled heads
                    self._dead -= 1
                # idle only when no timers AND no pool work in flight:
                # a pending run_in_thread completion arrives via
                # call_soon_threadsafe and must not be dropped by an
                # early exit
                if not self._heap and self._in_flight == 0 \
                        and until is None:
                    return None
                timeout = 0.1  # bounded: external work may arrive anytime
                if self._heap:
                    timeout = min(
                        timeout,
                        max(0.0, (self._heap[0][0] - self._wall()) / 1e9))
                self._cond.wait(timeout=timeout)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
