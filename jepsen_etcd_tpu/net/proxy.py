"""LinkProxy: one userspace TCP proxy in front of one node's port.

The unprivileged stand-in for the reference's iptables/netns layer
(nemesis.clj partitions drop packets with iptables over SSH): a
listening socket plus per-connection splice threads that consult a
router callback *per chunk*, so fault rules apply dynamically to
long-lived connections (raft streams, watch streams) the moment the
nemesis flips them — exactly like a kernel DROP rule appearing
mid-flight.

Semantics per direction (each TCP connection has two independently
ruled legs — upstream ``src -> node`` and downstream ``node -> src``):

- ``drop``       blackhole: bytes are read and discarded, the TCP
                 connection stays "up" (connects succeed, requests
                 hang until the client times out — iptables DROP, not
                 REJECT);
- ``drop_prob``  lossy link: each chunk is independently discarded
                 with this probability (netem-loss analog). Decisions
                 draw from the plane's seeded RNG via ``jitter()``, so
                 a given seed yields a reproducible drop pattern for a
                 given chunk sequence;
- ``latency_s``  + ``jitter_s``: each chunk sleeps ``latency +
                 U(0, jitter)`` before forwarding. One pump thread per
                 direction, so delivery stays FIFO under jitter;
- ``bandwidth_bps``  serialization delay of ``len(chunk)/bps``;
- ``slow_close_s``   a peer's FIN is held this long before the
                 half-close propagates.

Source attribution (who is dialing this node?) is sniffed from the
first bytes of ``kind="peer"`` connections and resolved by the plane:
the fake-etcd prober leads with a ``FAKE-ETCD-PEER <name>\\n``
preamble; real etcd's rafthttp requests carry an ``X-Server-From:
<member-id-hex>`` header the plane maps to a node name after setup
(member ids are only known once the real cluster has formed); and
checker-service TCP clients lead with ``JET-HOST <name>\\n``
(runner/transport.py), so the fleet's own control traffic partitions
exactly like SUT peer traffic. Sniffed bytes are always forwarded
(subject to the rules) — the sniff peeks, it never consumes.
Unattributable peer connections get ``src=None`` and are never
directionally dropped; ``kind="client"`` connections are attributed
``src="client"`` with no sniff.

Wall-clock and sleeps here are transport I/O, never verdict input
(net/* is DET-allowlisted in lint/policy.py); every shared attribute a
worker thread touches is written under ``self._lock``.
"""

from __future__ import annotations

import socket
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

#: splice chunk size; one rule consultation per chunk
CHUNK = 65536

#: how long the sniffer waits for attributable first bytes before
#: passing the connection through unattributed
SNIFF_TIMEOUT_S = 1.0

#: the fake-etcd prober's attribution preamble (round-tripped: the
#: peer listener answers FAKE-ETCD-OK <name>)
PEER_PREAMBLE = b"FAKE-ETCD-PEER "

#: real etcd rafthttp sender attribution header (lowercase for the
#: case-insensitive scan)
SERVER_FROM = b"x-server-from:"

#: checker-service host preamble (runner/transport.py PREAMBLE): the
#: generator host announces itself before its first frame
SVC_PREAMBLE = b"JET-HOST "

_UNDECIDED = object()


@dataclass(frozen=True)
class Rule:
    """The fault policy for one link direction at one instant."""

    drop: bool = False
    drop_prob: float = 0.0
    latency_s: float = 0.0
    jitter_s: float = 0.0
    bandwidth_bps: float = 0.0
    slow_close_s: float = 0.0


#: the no-fault rule (what route() returns on a healthy plane)
PASS = Rule()


def _close(sock: Optional[socket.socket]) -> None:
    if sock is None:
        return
    try:
        sock.close()
    except OSError:
        pass


class LinkProxy:
    """One listening proxy fronting ``target_host:target_port``.

    ``router(src, dst, kind) -> Rule`` is consulted for every chunk on
    every leg; ``resolve(member_id_hex) -> name`` maps real-etcd
    X-Server-From values; ``jitter() -> float`` draws from the plane's
    seeded RNG; ``on_event(event, value)`` feeds telemetry counters.
    """

    def __init__(self, node: str, kind: str, target_port: int,
                 router: Callable[[Optional[str], str, str], Rule],
                 resolve: Optional[Callable[[str], Optional[str]]] = None,
                 jitter: Optional[Callable[[], float]] = None,
                 on_event: Optional[Callable[[str, float], None]] = None,
                 target_host: str = "127.0.0.1",
                 listen_host: str = "127.0.0.1"):
        self.node = node
        self.kind = kind
        self.target_host = target_host
        self.target_port = target_port
        self.router = router
        self.resolve = resolve or (lambda ident: None)
        self.jitter = jitter or (lambda: 0.0)
        self.on_event = on_event or (lambda event, value: None)
        self._lock = threading.Lock()
        self._closed = False
        #: live connections: [dsock, usock, legs_remaining] — both
        #: sockets are closed and the entry pruned once both pump legs
        #: have drained (the fake-etcd prober opens fresh connections
        #: every 0.25s, so anything short of eager cleanup leaks fds)
        self._conns: list[list] = []
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((listen_host, 0))
        self._lsock.listen(128)
        self.port = self._lsock.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"net-accept-{node}-{kind}")
        self._accept_thread.start()

    # ---- accept / per-connection handling ----------------------------------

    def _accept_loop(self) -> None:
        while True:
            try:
                dsock, _ = self._lsock.accept()
            except OSError:
                with self._lock:
                    if self._closed:
                        return  # listener closed by close()
                # transient accept failure (EMFILE, ECONNABORTED, ...):
                # dying here would be a permanent unhealable partition,
                # so surface it and keep serving
                self.on_event("accept_error", 1)
                time.sleep(0.05)
                continue
            t = threading.Thread(target=self._handle, args=(dsock,),
                                 daemon=True,
                                 name=f"net-conn-{self.node}-{self.kind}")
            t.start()

    def _handle(self, dsock: socket.socket) -> None:
        src: Optional[str] = "client"
        initial = b""
        if self.kind == "peer":
            src, initial = self._sniff(dsock)
        try:
            usock = socket.create_connection(
                (self.target_host, self.target_port), timeout=2.0)
        except OSError:
            # node down (killed): the proxy stays up, the dial fails —
            # clients see a reset, same as a dead node behind a LB
            self.on_event("dropped", 1)
            _close(dsock)
            return
        entry = [dsock, usock, 2]  # two pump legs outstanding
        with self._lock:
            if self._closed:
                _close(dsock)
                _close(usock)
                return
            self._conns.append(entry)
        down = threading.Thread(
            target=self._run_pump,
            args=(entry, usock, dsock, self.node, src),
            daemon=True, name=f"net-pump-{self.node}-{self.kind}")
        down.start()
        # upstream leg runs on this connection thread
        self._run_pump(entry, dsock, usock, src, self.node, initial)

    def _run_pump(self, entry: list, rsock: socket.socket,
                  wsock: socket.socket, src: Optional[str], dst: str,
                  initial: bytes = b"") -> None:
        try:
            self._pump(rsock, wsock, src, dst, initial)
        finally:
            self._leg_done(entry)

    def _leg_done(self, entry: list) -> None:
        """One pump leg finished; when both have, close both sockets
        and forget the connection (clean-EOF legs only half-close in
        _pump, so without this every finished connection leaks fds)."""
        with self._lock:
            entry[2] -= 1
            done = entry[2] <= 0
            if done:
                try:
                    self._conns.remove(entry)
                except ValueError:
                    pass  # already pruned by close()
        if done:
            _close(entry[0])
            _close(entry[1])

    # ---- attribution sniffing ----------------------------------------------

    def _attribute(self, buf: bytes):
        """``_UNDECIDED`` (need more bytes), a node name, or None
        (unattributable — pass through undropped)."""
        for preamble in (PEER_PREAMBLE, SVC_PREAMBLE):
            head = buf[:len(preamble)]
            if not preamble.startswith(head):
                continue
            # a line preamble (fake-etcd prober or checker-service
            # host announcement) — or a prefix of one
            if not buf.startswith(preamble):
                return _UNDECIDED
            nl = buf.find(b"\n")
            if nl < 0:
                return _UNDECIDED if len(buf) < 256 else None
            return buf[len(preamble):nl].decode(
                "utf-8", "replace").strip() or None
        # HTTP request (real etcd rafthttp): scan the header block
        lower = buf.lower()
        at = lower.find(SERVER_FROM)
        if at >= 0:
            eol = buf.find(b"\r\n", at)
            if eol < 0:
                return _UNDECIDED
            ident = buf[at + len(SERVER_FROM):eol].decode(
                "ascii", "replace").strip().lower()
            return self.resolve(ident)
        if b"\r\n\r\n" in lower:
            return None  # full header block, no attribution header
        return _UNDECIDED

    def _sniff(self, sock: socket.socket) -> tuple[Optional[str], bytes]:
        sock.settimeout(SNIFF_TIMEOUT_S)
        buf = b""
        src: Optional[str] = None
        try:
            for _ in range(8):
                chunk = sock.recv(4096)
                if not chunk:
                    break
                buf += chunk
                got = self._attribute(buf)
                if got is not _UNDECIDED:
                    src = got
                    break
                if len(buf) >= CHUNK:
                    break
        except OSError:
            pass
        try:
            sock.settimeout(None)
        except OSError:
            pass
        return src, buf

    # ---- splice pumps ------------------------------------------------------

    def _forward(self, data: bytes, wsock: socket.socket,
                 src: Optional[str], dst: str, state: dict) -> None:
        rule = self.router(src, dst, self.kind)
        if rule.drop:
            if not state.get("dropped"):
                state["dropped"] = True
                self.on_event("dropped", 1)
            return  # blackhole: discard, keep reading
        if rule.drop_prob > 0 and self.jitter() < rule.drop_prob:
            # lossy link: this chunk vanishes but the connection stays
            # up — TCP-level loss seen by the application as a stall or
            # a torn stream, not a closed socket
            self.on_event("chunk_dropped", len(data))
            return
        delay = rule.latency_s
        if rule.jitter_s:
            delay += rule.jitter_s * self.jitter()
        if rule.bandwidth_bps > 0:
            delay += len(data) / rule.bandwidth_bps
        if delay > 0:
            time.sleep(delay)
            self.on_event("delayed", len(data))
        wsock.sendall(data)

    def _pump(self, rsock: socket.socket, wsock: socket.socket,
              src: Optional[str], dst: str, initial: bytes = b"") -> None:
        state: dict = {}
        try:
            pending = initial
            while True:
                if pending:
                    self._forward(pending, wsock, src, dst, state)
                pending = rsock.recv(CHUNK)
                if not pending:
                    break
            rule = self.router(src, dst, self.kind)
            if rule.slow_close_s > 0:
                time.sleep(rule.slow_close_s)
            try:
                wsock.shutdown(socket.SHUT_WR)
            except OSError:
                pass
        except OSError:
            _close(rsock)
            _close(wsock)

    # ---- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            conns = list(self._conns)
            self._conns.clear()
        _close(self._lsock)
        for dsock, usock, _legs in conns:
            _close(dsock)
            _close(usock)
