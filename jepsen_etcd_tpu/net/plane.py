"""NetPlane: the controller for the per-link proxy fleet.

One NetPlane per local cluster (db/local.py owns it when
``--net-proxy`` is active): ``front()`` raises a LinkProxy in front of
each node's real client and peer port, and the fault API below is what
the nemesis partition/latency packages drive in local mode — the same
vocabulary as the simulated ``Cluster`` (``partition`` /
``partition_pairs`` / ``heal_partition`` / ``set_latency`` /
``clear_latency``), so ``nemesis/faults.py`` dispatches to either
backend without caring which.

Blocked-pair encoding is shared with ``sut/cluster.py``: a
``frozenset((a, b))`` blocks both directions, an ordered tuple
``(src, dst)`` blocks only ``src -> dst`` (one-way / asymmetric
partitions). Only ``kind="peer"`` legs are ever dropped — partitions
sever inter-node traffic, clients always reach their own node — while
latency/bandwidth/slow-close apply to every leg (tc-on-the-interface
semantics).

Telemetry: ``net.links`` (proxies raised), ``net.dropped_conns``
(connections blackholed or refused), ``net.dropped_chunks`` (chunks
lost to probabilistic drop), ``net.delayed_bytes`` (bytes that paid
injected latency), ``net.active_rules`` (peak concurrent fault
rules), ``net.accept_errors`` (transient accept() failures survived)
— all in the runner/telemetry.py REGISTRY.

The jitter RNG is a plane-owned seeded ``random.Random`` (DET002:
no unseeded randomness, even off the verdict path).
"""

from __future__ import annotations

import random
import threading
from typing import Iterable, Optional

from ..runner import telemetry
from .proxy import LinkProxy, Rule, PASS


class NetPlane:
    """Fault controller over the local cluster's proxy fleet."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        #: (node, kind) -> LinkProxy
        self.proxies: dict[tuple[str, str], LinkProxy] = {}
        #: node names with at least one proxy raised
        self.nodes: set[str] = set()
        #: blocked pairs: frozensets (bidirectional) + tuples (one-way)
        self.blocked: set = set()
        #: (latency_s, jitter_s) when a latency fault is active
        self.latency: Optional[tuple[float, float]] = None
        self.bandwidth_bps: float = 0.0
        self.slow_close_s: float = 0.0
        #: per-chunk loss probability when a lossy-link fault is active
        self.drop_prob: float = 0.0
        #: real-etcd member-id (hex string) -> node name, registered by
        #: db/local.py once the cluster has formed and ids are known
        self.member_names: dict[str, str] = {}
        self._closed = False

    # ---- fleet -------------------------------------------------------------

    def front(self, node: str, kind: str, target_port: int,
              target_host: str = "127.0.0.1") -> int:
        """Raise a proxy in front of ``node``'s real ``kind`` port;
        returns the proxy's listen port (what gets advertised)."""
        proxy = LinkProxy(node, kind, target_port,
                          router=self.route, resolve=self.member_name,
                          jitter=self._jitter, on_event=self._note,
                          target_host=target_host)
        with self._lock:
            self.proxies[(node, kind)] = proxy
            self.nodes.add(node)
        telemetry.current().counter("net.links", 1)
        return proxy.port

    def front_service(self, target_port: int, node: str = "svc",
                      target_host: str = "127.0.0.1") -> str:
        """Raise a proxy in front of a checker-service TCP port and
        return the endpoint clients should dial (``tcp://...``).
        Service legs ride ``kind="peer"`` so partitions — e.g.
        ``partition_pairs({frozenset((host, node))})`` — sever the
        fleet's own control traffic with SUT semantics; attribution
        comes from the client's ``JET-HOST`` preamble."""
        port = self.front(node, "peer", target_port,
                          target_host=target_host)
        return f"tcp://127.0.0.1:{port}"

    def register_member_ids(self, mapping: dict[str, str]) -> None:
        """Install real-etcd member-id-hex -> node-name attribution
        (X-Server-From values are member ids, only known post-setup)."""
        with self._lock:
            for ident, name in sorted(mapping.items()):
                self.member_names[str(ident).lower()] = name

    def member_name(self, ident: str) -> Optional[str]:
        with self._lock:
            return self.member_names.get(str(ident).lower())

    def _jitter(self) -> float:
        with self._lock:
            return self._rng.random()

    # ---- routing (called from pump threads, per chunk) ---------------------

    def route(self, src: Optional[str], dst: str, kind: str) -> Rule:
        with self._lock:
            blocked = self.blocked
            drop = bool(blocked) and kind == "peer" and src is not None \
                and ((src, dst) in blocked
                     or frozenset((src, dst)) in blocked)
            lat = self.latency
            bw = self.bandwidth_bps
            sc = self.slow_close_s
            dp = self.drop_prob
        if not (drop or lat or bw or sc or dp):
            return PASS
        return Rule(drop=drop, drop_prob=dp,
                    latency_s=lat[0] if lat else 0.0,
                    jitter_s=lat[1] if lat else 0.0,
                    bandwidth_bps=bw, slow_close_s=sc)

    # ---- fault API (the nemesis backend surface) ---------------------------

    def partition(self, groups: list[list[str]]) -> None:
        """Partition nodes into isolated groups (bidirectional), same
        group semantics as sut/cluster.py: nodes in no group are cut
        off from every grouped node."""
        group_of = {}
        for gi, g in enumerate(groups):
            for name in g:
                group_of[name] = gi
        with self._lock:
            names = sorted(self.nodes | set(group_of))
        pairs = set()
        for i, a in enumerate(names):
            for b in names[i + 1:]:
                if group_of.get(a) != group_of.get(b):
                    pairs.add(frozenset((a, b)))
        self.partition_pairs(pairs)

    def partition_pairs(self, pairs: Iterable) -> None:
        """Install an explicit blocked set: frozensets block both ways,
        ordered (src, dst) tuples block only src -> dst."""
        with self._lock:
            self.blocked = set(pairs)
        self._note_rules()

    def heal_partition(self) -> None:
        with self._lock:
            self.blocked = set()
        self._note_rules()

    def set_latency(self, delta_ms: float, jitter_ms: float = 0) -> None:
        with self._lock:
            self.latency = (delta_ms / 1000.0, jitter_ms / 1000.0)
        self._note_rules()

    def clear_latency(self) -> None:
        with self._lock:
            self.latency = None
        self._note_rules()

    def set_bandwidth(self, bps: float) -> None:
        with self._lock:
            self.bandwidth_bps = float(bps)
        self._note_rules()

    def set_slow_close(self, seconds: float) -> None:
        with self._lock:
            self.slow_close_s = float(seconds)
        self._note_rules()

    def set_drop_prob(self, p: float) -> None:
        """Lossy-link fault: every chunk on every leg is independently
        discarded with probability ``p`` (clamped to [0, 1]), drawn
        from the plane's seeded RNG."""
        with self._lock:
            self.drop_prob = min(1.0, max(0.0, float(p)))
        self._note_rules()

    def clear_drop_prob(self) -> None:
        with self._lock:
            self.drop_prob = 0.0
        self._note_rules()

    def heal(self) -> None:
        """Drop every active rule (partitions, latency, caps)."""
        with self._lock:
            self.blocked = set()
            self.latency = None
            self.bandwidth_bps = 0.0
            self.slow_close_s = 0.0
            self.drop_prob = 0.0
        self._note_rules()

    # ---- accounting --------------------------------------------------------

    def _active_rules(self) -> int:
        # caller holds no lock; snapshot under it
        with self._lock:
            return (len(self.blocked) + (1 if self.latency else 0)
                    + (1 if self.bandwidth_bps else 0)
                    + (1 if self.slow_close_s else 0)
                    + (1 if self.drop_prob else 0))

    def _note_rules(self) -> None:
        telemetry.current().counter("net.active_rules",
                                    self._active_rules(), mode="max")

    def _note(self, event: str, value: float) -> None:
        """Proxy-thread event sink -> REGISTRY counters (literal names:
        dashboards join by name, graftlint TEL002 checks them)."""
        if event == "dropped":
            telemetry.current().counter("net.dropped_conns", value)
        elif event == "chunk_dropped":
            telemetry.current().counter("net.dropped_chunks", value)
        elif event == "delayed":
            telemetry.current().counter("net.delayed_bytes", value)
        elif event == "accept_error":
            telemetry.current().counter("net.accept_errors", value)

    def stats(self) -> dict:
        with self._lock:
            return {
                "links": len(self.proxies),
                "nodes": sorted(self.nodes),
                "blocked": len(self.blocked),
                "latency": self.latency,
                "bandwidth_bps": self.bandwidth_bps,
                "slow_close_s": self.slow_close_s,
                "drop_prob": self.drop_prob,
            }

    # ---- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            proxies = [self.proxies[k] for k in sorted(self.proxies)]
        for p in proxies:
            p.close()
