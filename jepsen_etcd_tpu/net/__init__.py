"""Userspace network fault plane for `--db local`.

A per-link TCP proxy fleet (proxy.py) fronted by one NetPlane
controller (plane.py): every peer->peer and client->node URL in local
mode routes through a proxy, so partitions, one-way drops, latency,
bandwidth caps, and slow-close become plain userspace socket policy —
no netns/iptables privileges needed.
"""

from .plane import NetPlane
from .proxy import LinkProxy, Rule

__all__ = ["NetPlane", "LinkProxy", "Rule"]
