"""CLI: `python -m jepsen_etcd_tpu test|test-all ...`.

Mirrors the reference's lein run commands and flags (etcd.clj:157-257):
test runs one composed test; test-all sweeps nemeses x workloads.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

from .compose import etcd_test, default_opts
from .workloads import workloads, ALL_WORKLOADS, WORKLOADS_EXPECTED_TO_PASS
from .runner.test_runner import run_test

# nemesis combinations swept by test-all (etcd.clj:60-73)
ALL_NEMESES = [
    ["admin"],
    ["pause", "admin"],
    ["kill", "admin"],
    ["partition", "admin"],
    ["latency", "admin"],
    ["member", "admin"],
    ["bitflip-wal", "bitflip-snap", "admin"],
    ["bitflip-wal", "bitflip-snap", "kill"],
    ["admin", "bitflip-snap", "bitflip-wal", "pause", "kill", "partition",
     "latency", "clock", "member"],
]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="jepsen_etcd_tpu")
    sub = p.add_subparsers(dest="command", required=True)
    subs = {}
    for cmd in ("test", "test-all", "campaign"):
        s = sub.add_parser(cmd)
        subs[cmd] = s
        # None means "register" for test, "all workloads" for test-all
        # (the reference's test-all honors -w as a narrowing filter,
        # etcd.clj:238-242)
        s.add_argument("-w", "--workload", default=None,
                       choices=sorted(workloads().keys()))
        s.add_argument("--nemesis", default="",
                       help="comma-separated faults: kill,pause,partition,"
                            "latency,clock,member,corrupt,admin,all,none")
        s.add_argument("--nemesis-interval", type=float, default=5.0)
        s.add_argument("-r", "--rate", type=float, default=200.0)
        s.add_argument("--ops-per-key", type=int, default=200)
        s.add_argument("--time-limit", type=float, default=30.0)
        s.add_argument("-c", "--concurrency", default=None,
                       help="worker count; suffix n multiplies node count "
                            "(e.g. 4n)")
        s.add_argument("--nodes", default="n1,n2,n3,n4,n5")
        s.add_argument("--serializable", action="store_true")
        s.add_argument("--lazyfs", action="store_true")
        s.add_argument("--client-type", default="direct",
                       choices=["direct", "etcdctl", "http", "grpc"],
                       help="direct/etcdctl drive the simulated cluster; "
                            "http drives a LIVE etcd over its v3 JSON "
                            "gateway, grpc over native gRPC — the "
                            "reference's wire protocol "
                            "(etcd.clj:246-257, client.clj:14-68)")
        s.add_argument("--endpoint", default="http://127.0.0.1:2379",
                       help="comma-separated live-etcd endpoint URLs "
                            "(only with --client-type http/grpc); each "
                            "endpoint is a node")
        s.add_argument("--db", default=None,
                       choices=["sim", "live", "local"],
                       help="cluster lifecycle driver: sim (default for "
                            "direct/etcdctl), live (external cluster at "
                            "--endpoint, no fault control plane), local "
                            "(spawn+supervise etcd processes on this "
                            "machine — kill/pause/member/admin faults "
                            "work; default for http/grpc is live)")
        s.add_argument("--etcd-binary", default=None,
                       help="--db local: etcd argv (shell-split). "
                            "Default: etcd from PATH if present, else "
                            "the bundled fake-etcd stub; 'fake' forces "
                            "the stub")
        s.add_argument("--etcd-data-dir", default=None,
                       help="--db local: root for per-node data dirs "
                            "and logs (default: a fresh temp dir)")
        s.add_argument("--net-proxy", action="store_true",
                       help="--db local: front every peer/client URL "
                            "with the userspace TCP proxy plane "
                            "(net/plane.py) even when no network fault "
                            "is requested; partition/latency faults "
                            "raise it automatically")
        s.add_argument("--snapshot-count", type=int, default=100)
        s.add_argument("--unsafe-no-fsync", action="store_true",
                       help="ask the SUT not to fsync WAL appends "
                            "(etcd.clj:204)")
        s.add_argument("--corrupt-check", action="store_true",
                       help="enable the runtime corruption monitor: "
                            "initial check at boot + a sweep every "
                            "virtual minute (etcd.clj:164, db.clj:97-99)")
        s.add_argument("-v", "--version", default="sim-3.5.6",
                       help="SUT version to run (etcd.clj:206-207; the "
                            "sim ships exactly one)")
        s.add_argument("--seed", type=int, default=0)
        s.add_argument("--debug", action="store_true")
        s.add_argument("--tcpdump", action="store_true",
                       help="record a message-level network trace to "
                            "store/<run>/trace.jsonl (db.clj:276-277)")
        s.add_argument("--no-telemetry", action="store_true",
                       help="skip writing store/<run>/telemetry.jsonl "
                            "(phase/checker spans and kernel counters "
                            "are on by default)")
        s.add_argument("--stream", action="store_true",
                       help="online chunked checking: feed recorded op "
                            "columns to checker front-ends while "
                            "generation runs; verdicts stay "
                            "bit-identical to post-hoc")
        s.add_argument("--stream-chunk-ops", type=int, default=1024,
                       help="recorded ops per streamed chunk "
                            "(default 1024)")
        s.add_argument("--soak", action="store_true",
                       help="sliding-window soak against ONE long-lived "
                            "cluster (--client-type http/grpc): each "
                            "window is generated, streamed, checked and "
                            "released before the next, so memory stays "
                            "bounded indefinitely")
        s.add_argument("--soak-windows", type=int, default=0,
                       help="number of soak windows (0 = run until "
                            "interrupted)")
        s.add_argument("--soak-window-s", type=float, default=None,
                       help="per-window time limit in seconds "
                            "(default: --time-limit)")
        s.add_argument("--soak-net-fault", action="append", default=None,
                       metavar="KIND[:ARG]",
                       help="long-lived net-plane fault schedule "
                            "(--db local): windows cycle through "
                            "[healthy] + these faults, each applied to "
                            "the proxy plane for the WHOLE window and "
                            "healed after. Kinds: latency[:delta-ms], "
                            "drop[:probability], partition. Repeatable")
        s.add_argument("--test-count", type=int, default=1)
        s.add_argument("--inject-stale-reads", action="store_true",
                       help="seed the sim's stale-read bug class "
                            "(epoch-v2 generator): reads may return "
                            "the pre-last-write snapshot — with "
                            "faults configured, only inside an open "
                            "partition window (the guided-campaign "
                            "target); with none, unconditionally")
        s.add_argument("--staleness-bound-s", type=float, default=None,
                       help="register-stale: max excusable read lag in "
                            "virtual seconds (default 8.0)")
        s.add_argument("--lease-ttl-ms", type=float, default=None,
                       help="lock-lease: lease TTL clipping certain-"
                            "hold windows (default 1500)")
        s.add_argument("--compact-keep", type=int, default=None,
                       help="compact-watch: revisions kept behind the "
                            "compaction watermark (default 8)")
        s.add_argument("--only-workloads-expected-to-pass",
                       action="store_true")
        s.add_argument("--store", default="store")
        s.add_argument("--checker-service", default=None,
                       help="AF_UNIX socket of a running checker "
                            "service (see the checker-service "
                            "subcommand): device-bound checks are "
                            "shipped there and batched across every "
                            "submitting run; unset = check in-process "
                            "(campaign hosts its own unless "
                            "--no-service)")
    camp = subs["campaign"]
    camp.add_argument("--pool", type=int, default=4,
                      help="worker processes running tests concurrently "
                           "(0 = inline in this process)")
    camp.add_argument("--no-service", action="store_true",
                      help="skip the shared checker service: every "
                           "worker dispatches its own device checks "
                           "(pays the per-run dispatch floor)")
    camp.add_argument("--no-live", action="store_true",
                      help="skip the live telemetry collector (no "
                           "live.sock/live.json, /live shows no "
                           "campaign); runs record exactly as before")
    camp.add_argument("--service-tick", type=float, default=0.05,
                      help="checker-service coalescing window in "
                           "seconds: pending packs from all runners "
                           "batch into one dispatch per (bucket, "
                           "width) per tick")
    camp.add_argument("--campaign-name", default="campaign",
                      help="store dir name for the campaign summary "
                           "(store/<name>/<id>/campaign.json)")
    camp.add_argument("--gen-epoch", default="epoch-v1",
                      choices=["epoch-v1", "epoch-v2", "epoch-v3"],
                      help="generator epoch (epoch ledger, runner/"
                           "sim.py): epoch-v2 routes every sim run "
                           "through the batched lockstep generator "
                           "(simbatch/) — S seeds per (workload, "
                           "nemesis) cell generated in one columnar "
                           "pass, histories born as OpColumns; "
                           "epoch-v3 runs the same cells through the "
                           "jitted device engine (simbatch/"
                           "engine_jax.py, jax.random draws, lax.scan "
                           "drain — MVCC workloads delegate to the "
                           "epoch-v2 sweep); runs "
                           "the batched generator cannot serve (live "
                           "clusters, unsupported workloads, --stream/"
                           "--soak) fall back to epoch-v1, and every "
                           "campaign.json row records the epoch that "
                           "actually produced it")
    camp.add_argument("--hosts", type=int, default=0,
                      help="multi-host fan-out: spawn N worker-agent "
                           "processes (host1..hostN) that pull runs "
                           "over loopback TCP and ship device checks "
                           "to the campaign's TCP checker service "
                           "with a campaign-minted auth token; "
                           "replaces --pool for the non-batched "
                           "specs (0 = local process pool)")
    camp.add_argument("--force-kernel", action="store_true",
                      help="disable the native-DFS size cutoff so "
                           "every key is device-bound (coalescing "
                           "demos/tests; production keeps the "
                           "measured routing)")
    camp.add_argument("--guided", type=int, default=0, metavar="N",
                      help="coverage-guided mode: spend a budget of N "
                           "runs adaptively instead of sweeping the "
                           "matrix uniformly — generation 0 "
                           "stratifies one run per cell, later "
                           "generations mutate a corpus of "
                           "novelty-scored ancestors (runner/"
                           "guided.py); failing schedules are "
                           "delta-debugged to minimal repros "
                           "(shrink.json). Forces gen-epoch epoch-v2 "
                           "for sim specs")
    camp.add_argument("--master-seed", type=int, default=None,
                      help="--guided: the search RNG seed (mutation/"
                           "crossover draws; default: --seed) — one "
                           "master seed fully determines the search")
    camp.add_argument("--corpus-in", default=None, metavar="PATH",
                      help="--guided: warm-start from a corpus "
                           "exported by --corpus-out — ancestors "
                           "join the pool and already-seen "
                           "signatures/cells/envelope peaks stop "
                           "scoring as novel")
    camp.add_argument("--corpus-out", default=None, metavar="PATH",
                      help="--guided: export the final novelty-scored "
                           "corpus (ancestors, envelope, signature/"
                           "cell ledgers) as JSON for a later "
                           "--corpus-in")
    cs = sub.add_parser("checker-service",
                        help="run a standalone batched TPU checker "
                             "service: one process owns the device; "
                             "concurrent test/campaign invocations "
                             "point --checker-service at its socket "
                             "and their device checks coalesce into "
                             "one dispatch per (bucket, width) per "
                             "tick")
    cs.add_argument("--socket", default=None,
                    help="AF_UNIX socket path (default: a fresh temp "
                         "path, printed on stdout)")
    cs.add_argument("--tick", type=float, default=0.05,
                    help="coalescing window seconds")
    cs.add_argument("--tcp", nargs="?", const=True, default=None,
                    metavar="[HOST:]PORT",
                    help="also listen on TCP for multi-host clients "
                         "(bare --tcp: loopback ephemeral port, "
                         "printed on stdout); pair with --token or "
                         "JEPSEN_ETCD_TPU_SERVICE_TOKEN so only the "
                         "fleet can submit")
    cs.add_argument("--token", default=None,
                    help="shared-secret auth token TCP clients must "
                         "present (default: env "
                         "JEPSEN_ETCD_TPU_SERVICE_TOKEN; unset = "
                         "unauthenticated)")
    wa = sub.add_parser("worker-agent",
                        help="one generator-host agent: registers "
                             "with a campaign's HostAgentPool over "
                             "TCP, pulls run specs, ships device "
                             "checks to the fleet's checker service, "
                             "returns summary rows (spawned by "
                             "campaign --hosts; rarely run by hand)")
    wa.add_argument("--connect", required=True,
                    help="the pool endpoint (tcp://HOST:PORT)")
    wa.add_argument("--host", required=True,
                    help="this agent's host name (row + ledger "
                         "attribution)")
    wa.add_argument("--token", default=None,
                    help="pool auth token (default: env "
                         "JEPSEN_ETCD_TPU_SERVICE_TOKEN)")
    srv = sub.add_parser("serve", help="serve the store dir over HTTP "
                                       "(etcd.clj:250-252)")
    srv.add_argument("--store", default="store")
    srv.add_argument("-p", "--port", type=int, default=8080)
    srv.add_argument("-b", "--bind", default="127.0.0.1")
    gw = sub.add_parser("gateway",
                        help="serve an etcd v3 JSON-gateway endpoint "
                             "backed by the simulated MVCC store (the "
                             "real-etcd adapter's hermetic test double)")
    gw.add_argument("-p", "--port", type=int, default=2379)
    gw.add_argument("--grpc", action="store_true",
                    help="serve native gRPC (etcdserverpb) instead of "
                         "the JSON gateway")
    tl = sub.add_parser("tel",
                        help="mine telemetry artifacts offline: span "
                             "percentile tables (default), --diff "
                             "two runs, --ledger a campaign dir, or "
                             "--coverage feature vectors; never "
                             "touches the jax backend")
    tl.add_argument("paths", nargs="+",
                    help="telemetry.jsonl/service.jsonl files, run "
                         "dirs, campaign dirs, or a store base "
                         "(--coverage)")
    tl.add_argument("--diff", action="store_true",
                    help="compare spans across exactly two inputs")
    tl.add_argument("--ledger", action="store_true",
                    help="verify a campaign's shipped/queue-wait/"
                         "trace-join accounting (exit 1 on mismatch)")
    tl.add_argument("--coverage", action="store_true",
                    help="emit the per-run + aggregate coverage "
                         "vector (frontier, wave depth, rungs, "
                         "spills, verdict signatures)")
    tl.add_argument("--corpus", action="store_true",
                    help="inspect a guided campaign (guided.json): "
                         "corpus ancestors, novel signatures, "
                         "minimized repros")
    tl.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    tl.add_argument("--no-index", action="store_true", dest="no_index",
                    help="bypass the store index and walk the tree "
                         "(output is bit-identical either way)")
    so = sub.add_parser("store",
                        help="artifact-store maintenance: build/verify "
                             "the sqlite run index, or compact old "
                             "passing runs to summaries; never touches "
                             "the jax backend")
    so.add_argument("action", choices=("index", "compact"),
                    help="index: verify (default) or --rebuild the "
                         "run index; compact: demote old passing runs "
                         "to index rows + summary files")
    so.add_argument("--store", default="store",
                    help="store base directory (default: store)")
    so.add_argument("--rebuild", action="store_true",
                    help="with `index`: backfill the index from the "
                         "tree in one transaction (also recurses into "
                         "guided sub-stores)")
    so.add_argument("--keep", type=int, default=32,
                    help="with `compact`: newest N runs spared "
                         "regardless of verdict (default 32)")
    so.add_argument("--dry-run", action="store_true", dest="dry_run",
                    help="with `compact`: report what would be "
                         "demoted without deleting anything")
    rp = sub.add_parser("replay",
                        help="re-execute a minimized repro "
                             "(shrink.json): regenerate the history "
                             "from the stored config + seed via the "
                             "batched generator, re-check it, and "
                             "verify the verdict signature matches "
                             "(exit 1 when it does not)")
    rp.add_argument("artifact",
                    help="path to a shrink.json store artifact (or a "
                         "run dir containing one)")
    return p


SPECIAL_NEMESES = {  # etcd.clj:75-80
    "none": [],
    "corrupt": ["bitflip-wal", "bitflip-snap", "truncate-wal"],
    "all": ["admin", "pause", "kill", "bitflip-wal", "bitflip-snap",
            "truncate-wal", "partition", "latency", "clock", "member"],
}


def parse_nemesis_spec(spec: str) -> list[str]:
    out: list[str] = []
    for tok in filter(None, (t.strip() for t in spec.split(","))):
        out.extend(SPECIAL_NEMESES.get(tok, [tok]))
    return sorted(set(out))


def opts_from_args(args) -> dict:
    db_mode = getattr(args, "db", None)
    if args.client_type in ("http", "grpc") and db_mode != "local":
        # live mode: nodes ARE the endpoint URLs
        nodes = [e.strip() for e in args.endpoint.split(",") if e.strip()]
    else:
        # sim and local modes: nodes are NAMES (local maps name ->
        # client URL in db/local.py)
        nodes = [n.strip() for n in args.nodes.split(",") if n.strip()]
    conc = args.concurrency
    if isinstance(conc, str):
        if conc.endswith("n"):
            conc = int(conc[:-1] or 1) * len(nodes)
        else:
            conc = int(conc)
    return {
        "nodes": nodes,
        "workload": args.workload or "register",
        "nemesis": parse_nemesis_spec(args.nemesis),
        "nemesis_interval": args.nemesis_interval,
        "rate": args.rate,
        "ops_per_key": args.ops_per_key,
        "time_limit": args.time_limit,
        "concurrency": conc,
        "serializable": args.serializable,
        "lazyfs": args.lazyfs,
        "client_type": args.client_type,
        "db_mode": db_mode,
        "etcd_binary": getattr(args, "etcd_binary", None),
        "etcd_data_dir": getattr(args, "etcd_data_dir", None),
        "net_proxy": getattr(args, "net_proxy", False),
        "snapshot_count": args.snapshot_count,
        "unsafe_no_fsync": args.unsafe_no_fsync,
        "corrupt_check": args.corrupt_check,
        "version": args.version,
        "seed": args.seed,
        "debug": args.debug,
        "tcpdump": args.tcpdump,
        "no_telemetry": getattr(args, "no_telemetry", False),
        "inject_stale_reads": getattr(args, "inject_stale_reads",
                                      False),
        "checker_service": getattr(args, "checker_service", None),
        "stream": getattr(args, "stream", False),
        "stream_chunk_ops": getattr(args, "stream_chunk_ops", 1024),
        "soak": getattr(args, "soak", False),
        "soak_windows": getattr(args, "soak_windows", 0),
        "soak_window_s": getattr(args, "soak_window_s", None),
        "soak_net_faults": getattr(args, "soak_net_fault", None) or [],
        "store_base": args.store,
        # MVCC surface thresholds: only carried when given, so
        # compose.default_opts keeps supplying the reference values
        **{k: v for k, v in (
            ("staleness_bound_s", getattr(args, "staleness_bound_s",
                                          None)),
            ("lease_ttl_ms", getattr(args, "lease_ttl_ms", None)),
            ("compact_keep", getattr(args, "compact_keep", None)),
        ) if v is not None},
    }


def test_all_matrix(args) -> tuple[list, list]:
    """The test-all sweep axes, narrowed by -w / --nemesis when given
    (all-tests, etcd.clj:236-242: a single workload or nemesis combo
    replaces the full axis)."""
    if args.workload:
        wls = [args.workload]
    elif args.only_workloads_expected_to_pass:
        wls = list(WORKLOADS_EXPECTED_TO_PASS)
    else:
        wls = list(ALL_WORKLOADS)
    nemeses = [parse_nemesis_spec(args.nemesis)] if args.nemesis \
        else ALL_NEMESES
    return wls, nemeses


def run_one(opts: dict) -> dict:
    test = etcd_test(opts)
    out = run_test(test)
    print(json.dumps({
        "test": test["name"],
        "valid?": out["valid?"],
        "ops": len(out["history"]),
        "sim-seconds": round(out["sim-seconds"], 1),
        "wall-seconds": round(out["wall-seconds"], 2),
        "dir": out["dir"],
    }))
    return out


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    args = build_parser().parse_args(argv)
    if args.command == "serve":
        from .serve import serve_store
        return serve_store(args.store, args.port, args.bind)
    if args.command == "tel":
        from .tel_cli import run as tel_run
        return tel_run(args)
    if args.command == "store":
        from .runner.store_index import cli_store
        return cli_store(args)
    if args.command == "gateway":
        log = logging.getLogger("jepsen_etcd_tpu")
        if args.grpc:
            import time as _time
            from .sut.grpc_gateway import serve_grpc
            srv, _state, port = serve_grpc(args.port)
            log.info("etcd v3 gRPC gateway on 127.0.0.1:%d (sim store)",
                     port)
            try:
                while True:
                    _time.sleep(3600)
            except KeyboardInterrupt:
                srv.stop(0)
            return 0
        from .sut.http_gateway import serve as gw_serve
        srv, _state = gw_serve(args.port)
        log.info(
            "etcd v3 gateway on http://127.0.0.1:%d (sim store)",
            srv.server_address[1])
        try:
            srv.serve_forever()
        except KeyboardInterrupt:
            pass
        return 0
    # kernel-running commands only: initializes the jax backend
    from .ops.common import enable_compile_cache
    enable_compile_cache()
    if args.command == "worker-agent":
        from .runner.host_agent import agent_main
        return agent_main(args.connect, args.host, token=args.token)
    if args.command == "checker-service":
        import time as _time
        from .runner.checker_service import CheckerService
        svc = CheckerService(path=args.socket, tick_s=args.tick,
                             tcp=args.tcp,
                             auth_token=args.token).start()
        print(json.dumps({"checker-service": svc.path,
                          "tcp": svc.tcp_endpoint}), flush=True)
        try:
            while True:
                _time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            svc.close()
        return 0
    if args.command == "replay":
        from .runner.shrink import replay_artifact
        path = args.artifact
        if os.path.isdir(path):
            path = os.path.join(path, "shrink.json")
        out = replay_artifact(path)
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0 if out["match"] else 1
    if args.command == "campaign":
        from .runner.campaign import campaign_specs, run_campaign
        base = opts_from_args(args)
        if args.force_kernel:
            base["force_kernel"] = True
        base["gen_epoch"] = args.gen_epoch
        wls, nemeses = test_all_matrix(args)
        if args.guided:
            from .runner.guided import run_guided

            def _print_guided_row(row):
                print(json.dumps({k: row.get(k) for k in
                                  ("index", "workload", "nemesis",
                                   "seed", "status", "valid", "dir")}))

            out = run_guided(
                base, wls, nemeses, budget=args.guided,
                seed0=args.seed, master_seed=args.master_seed,
                pool=args.pool,
                service=not args.no_service and not base.get(
                    "checker_service"),
                service_tick_s=args.service_tick,
                store_base=args.store,
                name=args.campaign_name
                if args.campaign_name != "campaign" else "guided",
                live=not args.no_live, hosts=args.hosts or None,
                on_row=_print_guided_row,
                corpus_in=args.corpus_in, corpus_out=args.corpus_out)
            print(json.dumps({
                "guided": out["name"], "dir": out["dir"],
                "budget": out["budget"], "runs": out["runs"],
                "generations": out["generations"],
                "signatures": out["signatures"],
                "first_failure_run": out["first_failure_run"],
                "corpus": len(out["corpus"]),
                "corpus_imported": out["corpus_imported"],
                "corpus_out": out["corpus_out"],
                "minimized": [{k: m.get(k) for k in
                               ("dir", "signature", "windows",
                                "nemesis_ops", "repro")}
                              for m in out["minimized"]],
                "wall_s": out["wall_s"],
            }))
            # a guided campaign EXISTS to find failures: exit 0 means
            # the search completed, not that every run passed
            return 0
        specs = campaign_specs(base, wls, nemeses,
                               runs_per_cell=args.test_count,
                               seed0=args.seed)

        def _print_row(row):
            print(json.dumps({k: row.get(k) for k in
                              ("index", "workload", "nemesis", "seed",
                               "status", "valid", "dir", "wall_s")}))

        out = run_campaign(
            specs, pool=args.pool,
            # an external service (--checker-service) rides in via the
            # per-spec opts; hosting one on top would shadow it
            service=not args.no_service and not base.get(
                "checker_service"),
            service_tick_s=args.service_tick,
            store_base=args.store, name=args.campaign_name,
            live=not args.no_live,
            hosts=args.hosts or None,
            on_row=_print_row)
        svc_counters = ((out.get("service") or {}).get("counters")
                        or {})
        print(json.dumps({
            "campaign": out["name"], "dir": out["dir"],
            "runs": out["count"], "valid?": out["valid?"],
            "failures": [repr(f) for f in out["failures"]],
            "wall_s": out["wall_s"],
            "service": {k: svc_counters[k] for k in sorted(svc_counters)
                        if k.startswith(("service.", "wgl.", "mxu."))}
            if svc_counters else None,
        }))
        return 0 if out["valid?"] else 1
    if args.command == "test":
        opts = opts_from_args(args)
        if opts.get("soak"):
            from .runner.test_runner import run_soak

            def _print_window(summary, _out):
                print(json.dumps(summary))
                return None

            try:
                out = run_soak(opts, on_window=_print_window)
            except KeyboardInterrupt:
                # interactive stop is the normal exit for
                # --soak-windows 0; the finally in run_soak already
                # tore the shared cluster down
                print(json.dumps({"soak": "interrupted"}))
                return 0
            print(json.dumps({"soak-windows": out["count"],
                              "valid?": out["valid?"]}))
            return 0 if out["valid?"] is True else 1
        ok = True
        for i in range(args.test_count):
            opts["seed"] = args.seed + i
            out = run_one(dict(opts))
            ok = ok and out["valid?"] is True
        return 0 if ok else 1
    # test-all: nemeses x workloads sweep (all-tests, etcd.clj:226-244)
    base = opts_from_args(args)
    wls, nemeses = test_all_matrix(args)
    failures = []
    for nem in nemeses:
        for wl in wls:
            for i in range(args.test_count):
                opts = dict(base)
                opts.update({"workload": wl, "nemesis": nem,
                             "seed": args.seed + i})
                try:
                    out = run_one(opts)
                    expected_pass = wl in WORKLOADS_EXPECTED_TO_PASS
                    if out["valid?"] is not True and expected_pass:
                        failures.append((wl, nem, out["valid?"]))
                except NotImplementedError as e:
                    print(f"SKIP {wl} {nem}: {e}")
    print(json.dumps({"failures": [repr(f) for f in failures]}))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
