"""graftlint CLI.

    python -m jepsen_etcd_tpu.lint [paths...] [--rule DET,COL...]
        [--json] [--baseline PATH] [--write-baseline] [--list-rules]

Exit 0 iff no non-suppressed, non-baselined findings (the tier-1
gate). Suppressed/baselined findings are shown only with --verbose.
"""

from __future__ import annotations

import argparse
import json
import sys

from .engine import (DEFAULT_BASELINE, META_RULES, load_baseline,
                     run_lint, write_baseline)
from .policy import Policy
from .rules import ALL_RULES, FAMILIES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m jepsen_etcd_tpu.lint",
        description="graftlint: determinism / columnar / JAX / "
                    "thread / telemetry static analysis")
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: the package)")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="ID|FAMILY",
                    help="restrict to rule ids or families "
                         "(comma-separable, repeatable)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    metavar="PATH",
                    help="baseline file (default: the committed one); "
                         "'' disables")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather all current findings into "
                         "--baseline and exit 0")
    ap.add_argument("--verbose", action="store_true",
                    help="also show suppressed/baselined findings")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for fam in FAMILIES:
            for rid in sorted(fam.RULES):
                print(f"{rid}  {fam.RULES[rid]}")
        for rid in sorted(META_RULES):
            print(f"{rid}  {META_RULES[rid]}")
        return 0

    rules = None
    if args.rule:
        rules = [r for part in args.rule for r in part.split(",") if r]
    try:
        report = run_lint(paths=args.paths or None, rules=rules,
                          baseline_path=args.baseline or None)
    except ValueError as e:   # unknown --rule selector
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        old = load_baseline(args.baseline)
        kept = write_baseline(args.baseline, report.findings, old)
        print(f"baseline: {len(kept)} entries -> {args.baseline}")
        return 0

    if args.as_json:
        print(json.dumps(report.to_dict(), indent=1))
        return 1 if report.errors else 0

    shown = report.findings if args.verbose else report.errors
    for f in shown:
        tag = " [suppressed]" if f.suppressed else (
            " [baselined]" if f.baselined else "")
        print(f"{f.location()}: {f.rule}{tag}: {f.message}")
        if f.snippet:
            print(f"    {f.snippet}")
    n = len(report.errors)
    print(f"graftlint: {report.files} files, "
          f"{len(report.rules_run)} rules, {n} error(s), "
          f"{sum(f.suppressed for f in report.findings)} suppressed, "
          f"{sum(f.baselined for f in report.findings)} baselined")
    return 1 if n else 0


if __name__ == "__main__":
    sys.exit(main())
