"""Lightweight name-based call-graph reachability.

DET rules must scope by *reachability* ("can SimLoop.run or a checker
``check()`` transitively hit this wall-clock call?"), not by directory
— `serve.py` reading `time.localtime` for a dashboard is fine; the
same call in a workload helper is a determinism hole even though both
live outside `runner/`.

Python call resolution is dynamic, so this graph over-approximates the
safe way: a call to ``foo(...)`` or ``x.foo(...)`` is an edge to EVERY
function or method named ``foo`` in the scanned tree. More reachable
means more scoped — a false edge can only make the lint stricter,
never let a violation escape. Operator tooling (cli/serve/forensics)
stays genuinely unreachable because nothing in the deterministic core
calls into it by any name.

Qualnames are ``module.path:Class.func`` (nested defs chain with
dots); module-level statements own the pseudo-def ``module:<module>``.
"""

from __future__ import annotations

import ast
from collections import deque
from typing import Iterable, Optional

MODULE_SCOPE = "<module>"


class _DefCollector(ast.NodeVisitor):
    """Collect defs + the simple names each def's body calls."""

    def __init__(self, modname: str):
        self.modname = modname
        self.stack: list[str] = []
        # innermost enclosing *function* qualname; class bodies run at
        # definition time in the enclosing scope, so their calls
        # attribute here, not to the class
        self.func_stack: list[str] = []
        # qualname -> set of called simple names
        self.calls: dict[str, set[str]] = {self._qual(MODULE_SCOPE): set()}
        # simple name -> set of qualnames
        self.defs: dict[str, set[str]] = {}
        # ast function node -> qualname (reused by rules for scoping)
        self.qual_of_node: dict[ast.AST, str] = {}

    def _qual(self, leaf: str) -> str:
        return f"{self.modname}:{'.'.join(self.stack + [leaf])}" \
            if self.stack else f"{self.modname}:{leaf}"

    def _current(self) -> str:
        if self.func_stack:
            return self.func_stack[-1]
        return f"{self.modname}:{MODULE_SCOPE}"

    def _visit_def(self, node) -> None:
        qual = self._qual(node.name)
        self.qual_of_node[node] = qual
        self.defs.setdefault(node.name, set()).add(qual)
        self.calls.setdefault(qual, set())
        self.stack.append(node.name)
        self.func_stack.append(qual)
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.func_stack.pop()
        self.stack.pop()

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.stack.pop()

    def visit_Call(self, node: ast.Call) -> None:
        name = None
        if isinstance(node.func, ast.Name):
            name = node.func.id
        elif isinstance(node.func, ast.Attribute):
            name = node.func.attr
        if name:
            self.calls[self._current()].add(name)
        # functions passed by reference (callbacks, Thread targets,
        # jit arguments) count as called: their bodies stay reachable
        for arg in list(node.args) + [k.value for k in node.keywords]:
            if isinstance(arg, ast.Name):
                self.calls[self._current()].add(arg.id)
            elif isinstance(arg, ast.Attribute):
                self.calls[self._current()].add(arg.attr)
        self.generic_visit(node)


class CallGraph:
    def __init__(self):
        self.calls: dict[str, set[str]] = {}
        self.defs: dict[str, set[str]] = {}
        self.qual_of_node: dict[ast.AST, str] = {}
        self._reachable: Optional[set[str]] = None

    def add_module(self, modname: str, tree: ast.AST) -> None:
        c = _DefCollector(modname)
        c.visit(tree)
        self.calls.update(c.calls)
        for name, quals in c.defs.items():
            self.defs.setdefault(name, set()).update(quals)
        self.qual_of_node.update(c.qual_of_node)

    def compute_reachable(self, roots: Iterable[str]) -> set[str]:
        """BFS over name-resolved edges from the given qualnames."""
        seen: set[str] = set()
        work = deque(roots)
        while work:
            q = work.popleft()
            if q in seen:
                continue
            seen.add(q)
            for name in self.calls.get(q, ()):
                for target in self.defs.get(name, ()):
                    if target not in seen:
                        work.append(target)
        self._reachable = seen
        return seen

    def reachable(self, qualname: str) -> bool:
        return self._reachable is None or qualname in self._reachable
