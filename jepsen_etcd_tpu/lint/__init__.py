"""graftlint: AST-level determinism, columnar-discipline, JAX-hygiene,
thread-safety, and telemetry-registry analysis for this repo.

Every correctness bar this port enforces — same-seed golden-hash
bit-identity, verdict equality at every stream chunk size, the
``dict_materializations == 0`` columnar guard — is a *dynamic* check:
a fuzz test has to happen to execute the offending path. graftlint is
the static twin: it proves at parse time that no wall-clock or
unseeded-random call is reachable from a verdict path, that columnar
modules never touch the dict op APIs, that no per-iteration ``jnp``
dispatch or retrace hazard hides in a host loop, that cross-thread
state on the stream-feed surface stays behind its lock, and that every
telemetry name in code exists in the canonical registry
(``runner/telemetry.py REGISTRY``) so ``/aggregate`` columns can't
silently go dark.

Usage::

    python -m jepsen_etcd_tpu.lint                 # whole package
    python -m jepsen_etcd_tpu.lint --rule DET      # one family
    python -m jepsen_etcd_tpu.lint --json          # machine output

Suppress a finding in place, with a reason::

    h.ops  # graftlint: ignore[COL001] dict fallback when columns absent

Suppressions without a reason are themselves findings (LINT002), and
suppressions whose rule no longer fires are flagged as orphans
(LINT001), so the ignore inventory can only shrink. Grandfathered
findings live in ``lint/baseline.json`` with a recorded reason each;
stale baseline entries are flagged (LINT004). The rule catalogue is
documented in STATIC_ANALYSIS.md.
"""

from .engine import Finding, Report, run_lint, load_baseline
from .policy import Policy

__all__ = ["Finding", "Report", "run_lint", "load_baseline", "Policy"]
