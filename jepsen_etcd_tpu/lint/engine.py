"""graftlint engine: module parsing, suppressions, baseline, runner.

The engine is pure ``ast`` + ``tokenize`` — it never imports the code
it scans (importing ops/ would pull in jax; importing workloads would
pull in the whole harness), so it runs in milliseconds under tier-1
and inside ``bench.py --dry``.

Suppression grammar (tokenized, so strings can't false-match)::

    expr  # graftlint: ignore[RULE1,RULE2] reason text

A standalone comment line applies to the next source line. The rule
list accepts exact ids (``COL001``) or families (``COL``). A
suppression must carry a reason (else LINT002), must suppress
something (else LINT001 orphan), and a baseline entry must still match
a live finding (else LINT004) — the grandfather inventory can only
shrink.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional

from .callgraph import CallGraph, MODULE_SCOPE
from .policy import Policy

#: engine-level findings (the meta-rules)
META_RULES = {
    "LINT000": "file does not parse",
    "LINT001": "orphan suppression: its rule no longer fires here",
    "LINT002": "suppression without a reason",
    "LINT004": "stale baseline entry: finding no longer exists",
}

_SUPPRESS_RE = re.compile(
    r"graftlint:\s*ignore\[([A-Za-z0-9_,\s]+)\]\s*(.*)")

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "baseline.json")


@dataclass
class Finding:
    rule: str
    path: str            # repo-relative posix path
    line: int
    col: int
    message: str
    snippet: str = ""    # stripped source line (baseline identity)
    suppressed: bool = False
    baselined: bool = False

    def fingerprint(self) -> str:
        ident = f"{self.rule}|{self.path}|{self.snippet}"
        return hashlib.sha1(ident.encode()).hexdigest()[:16]

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "snippet": self.snippet, "fingerprint": self.fingerprint(),
                "suppressed": self.suppressed,
                "baselined": self.baselined}


@dataclass
class Suppression:
    line: int            # the source line the suppression covers
    rules: tuple         # rule ids and/or families, upper-cased
    reason: str
    comment_line: int    # where the comment itself lives
    used: bool = False

    def covers(self, f: Finding) -> bool:
        if f.line != self.line:
            return False
        fam = f.rule.rstrip("0123456789")
        return f.rule in self.rules or fam in self.rules


class SourceModule:
    """One parsed file: tree, parent links, imports, suppressions."""

    def __init__(self, path: str, relpath: str, text: str):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.modname = relpath[:-3].replace("/", ".") \
            if relpath.endswith(".py") else relpath.replace("/", ".")
        self.tree = ast.parse(text)   # SyntaxError handled by caller
        self._parents: dict = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
        self.imports = self._collect_imports()
        self.suppressions = self._collect_suppressions()

    # -- structure -----------------------------------------------------------
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def enclosing_functions(self, node: ast.AST) -> list:
        """Enclosing function defs, innermost first."""
        out = []
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(cur)
            cur = self._parents.get(cur)
        return out

    def enclosing_loops(self, node: ast.AST) -> list:
        """For/While statements this node sits inside (within the same
        function — a loop outside the innermost def doesn't count, the
        def body runs once per call)."""
        out = []
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                out.append(cur)
            cur = self._parents.get(cur)
        return out

    def snippet_at(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule, path=self.relpath, line=line, col=col,
                       message=message, snippet=self.snippet_at(line))

    # -- imports -------------------------------------------------------------
    def _collect_imports(self) -> dict:
        """Local name -> dotted origin, e.g. ``wall_time`` -> ``time``,
        ``np`` -> ``numpy``, ``perf_counter`` -> ``time.perf_counter``."""
        out: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    out[a.asname or a.name] = f"{node.module}.{a.name}"
        return out

    def origin(self, node: ast.AST) -> Optional[str]:
        """Dotted origin of a Name/Attribute chain via the import
        table: ``wall_time.time`` -> ``time.time``; None when the root
        isn't an import."""
        parts = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        root = self.imports.get(cur.id)
        if root is None:
            return None
        return ".".join([root] + list(reversed(parts)))

    # -- suppressions --------------------------------------------------------
    def _collect_suppressions(self) -> list[Suppression]:
        out = []
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.text).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                rules = tuple(r.strip().upper()
                              for r in m.group(1).split(",") if r.strip())
                reason = m.group(2).strip()
                cline = tok.start[0]
                standalone = self.lines[cline - 1].lstrip().startswith("#")
                out.append(Suppression(
                    line=cline + 1 if standalone else cline,
                    rules=rules, reason=reason, comment_line=cline))
        except tokenize.TokenError:
            pass
        return out


@dataclass
class Report:
    findings: list = field(default_factory=list)
    files: int = 0
    rules_run: tuple = ()

    @property
    def errors(self) -> list:
        return [f for f in self.findings
                if not f.suppressed and not f.baselined]

    def to_dict(self) -> dict:
        return {"files": self.files,
                "rules": list(self.rules_run),
                "errors": len(self.errors),
                "suppressed": sum(f.suppressed for f in self.findings),
                "baselined": sum(f.baselined for f in self.findings),
                "findings": [f.to_dict() for f in self.findings]}


# -- baseline ----------------------------------------------------------------

def load_baseline(path: str) -> dict:
    """fingerprint -> entry dict; {} for a missing/empty file."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    return {e["fp"]: e for e in data.get("entries", [])}


def write_baseline(path: str, findings: Iterable[Finding],
                   old: Optional[dict] = None) -> dict:
    """Write non-suppressed findings as the new baseline, preserving
    reasons already recorded for surviving fingerprints."""
    old = old or {}
    entries = []
    seen = set()
    for f in findings:
        if f.suppressed:
            continue
        fp = f.fingerprint()
        if fp in seen:
            continue
        seen.add(fp)
        entries.append({"fp": fp, "rule": f.rule, "path": f.path,
                        "line": f.line, "snippet": f.snippet,
                        "reason": old.get(fp, {}).get(
                            "reason", "TODO: justify or fix")})
    data = {"version": 1, "entries": sorted(
        entries, key=lambda e: (e["path"], e["rule"], e["line"]))}
    with open(path, "w") as f:
        json.dump(data, f, indent=1)
        f.write("\n")
    return {e["fp"]: e for e in data["entries"]}


# -- registry extraction (TEL002 source) -------------------------------------

def extract_tel_registry(module: SourceModule) -> Optional[dict]:
    """Pull the literal REGISTRY assignment out of the telemetry module
    without importing it."""
    for node in module.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "REGISTRY":
                    try:
                        return ast.literal_eval(node.value)
                    except ValueError:
                        return None
    return None


# -- the runner --------------------------------------------------------------

class LintContext:
    """What every rule sees: policy, call graph, all modules."""

    def __init__(self, policy: Policy, graph: CallGraph,
                 modules: list[SourceModule]):
        self.policy = policy
        self.graph = graph
        self.modules = modules

    def reachable(self, module: SourceModule, node: ast.AST) -> bool:
        """Is the innermost def holding this node entry-reachable?
        Module-level code counts as reachable (import side effects run
        everywhere)."""
        encl = module.enclosing_functions(node)
        if not encl:
            return True
        qual = self.graph.qual_of_node.get(encl[0])
        if qual is None:
            return True
        return self.graph.reachable(qual)


def _iter_files(paths: Iterable[str], policy: Policy,
                root: str) -> list[tuple[str, str]]:
    out = []
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isfile(ap):
            rel = os.path.relpath(ap, root).replace(os.sep, "/")
            if not policy.excluded(_strip_pkg(rel)):
                out.append((ap, rel))
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                fp = os.path.join(dirpath, fn)
                rel = os.path.relpath(fp, root).replace(os.sep, "/")
                if policy.excluded(_strip_pkg(rel)):
                    continue
                out.append((fp, rel))
    return out


def _strip_pkg(rel: str) -> str:
    """Policy patterns are package-relative (``ops/wgl.py``); strip the
    leading ``jepsen_etcd_tpu/`` when scanning from the repo root."""
    prefix = "jepsen_etcd_tpu/"
    return rel[len(prefix):] if rel.startswith(prefix) else rel


def run_lint(paths: Optional[Iterable[str]] = None,
             rules: Optional[Iterable[str]] = None,
             baseline_path: Optional[str] = DEFAULT_BASELINE,
             policy: Optional[Policy] = None,
             root: Optional[str] = None) -> Report:
    """Run the analyzer. ``rules`` filters by family or exact id
    (None = all). Returns a Report; ``report.errors`` is the gate."""
    from . import rules as rules_pkg

    policy = policy or Policy()
    root = root or _default_root()
    if paths is None:
        paths = [os.path.join(root, "jepsen_etcd_tpu")]

    selected = rules_pkg.select(rules)
    report = Report(rules_run=tuple(sorted(
        r for fam in selected for r in fam.RULES)))

    modules: list[SourceModule] = []
    for fp, rel in _iter_files(paths, policy, root):
        try:
            with open(fp, encoding="utf-8") as f:
                text = f.read()
            modules.append(SourceModule(fp, _strip_pkg(rel), text))
        except SyntaxError as e:
            report.findings.append(Finding(
                rule="LINT000", path=_strip_pkg(rel),
                line=e.lineno or 1, col=e.offset or 0,
                message=f"file does not parse: {e.msg}"))
    report.files = len(modules)

    graph = CallGraph()
    for m in modules:
        graph.add_module(m.modname, m.tree)
    roots = [q for quals in graph.defs.values() for q in quals
             if policy.entry_point(q)]
    # a def no scanned code calls is externally callable — in a
    # partial scan (the bench gate lints two kernel modules) its real
    # callers are simply outside the module set. Rooting it keeps
    # reachability over-approximate, the strict direction.
    called: set = set()
    for names in graph.calls.values():
        called |= names
    roots += [q for name, quals in graph.defs.items()
              if name not in called for q in quals]
    if roots:
        graph.compute_reachable(roots)

    if policy.tel_registry is None:
        for m in modules:
            if policy.registry_module(m.relpath):
                policy.tel_registry = extract_tel_registry(m)

    ctx = LintContext(policy, graph, modules)
    families_run = {fam.FAMILY for fam in selected}
    for m in modules:
        for fam in selected:
            report.findings.extend(fam.check(m, ctx))

    # suppressions: mark covered findings, flag reasonless + orphans
    for m in modules:
        for sup in m.suppressions:
            for f in report.findings:
                if f.path == m.relpath and sup.covers(f):
                    f.suppressed = True
                    sup.used = True
            if not sup.reason:
                report.findings.append(Finding(
                    rule="LINT002", path=m.relpath, line=sup.comment_line,
                    col=0, message="suppression without a reason",
                    snippet=m.snippet_at(sup.comment_line)))
            elif not sup.used and any(
                    r.rstrip("0123456789") in families_run or
                    r in families_run for r in sup.rules):
                report.findings.append(Finding(
                    rule="LINT001", path=m.relpath, line=sup.comment_line,
                    col=0,
                    message="orphan suppression: "
                            f"{','.join(sup.rules)} no longer fires here",
                    snippet=m.snippet_at(sup.comment_line)))

    # baseline: grandfather matching fingerprints, flag stale entries
    baseline = load_baseline(baseline_path) if baseline_path else {}
    if baseline:
        live = set()
        for f in report.findings:
            if f.suppressed:
                continue
            fp = f.fingerprint()
            if fp in baseline:
                f.baselined = True
                live.add(fp)
        for fp, entry in baseline.items():
            if fp not in live:
                report.findings.append(Finding(
                    rule="LINT004", path=entry.get("path", "?"),
                    line=entry.get("line", 1), col=0,
                    message="stale baseline entry "
                            f"({entry.get('rule')}): finding no longer "
                            "exists — remove it",
                    snippet=entry.get("snippet", "")))

    report.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return report


def _default_root() -> str:
    """Repo root: the directory holding the ``jepsen_etcd_tpu`` package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
