"""TEL: telemetry discipline.

The cross-run dashboard joins counters by NAME across runs and
configs; a typo'd counter name silently creates a new series and the
dashboards read zero forever. And a span created but never entered
(``tel.span("x")`` as a bare statement instead of ``with
tel.span("x"):``) records nothing while looking instrumented.

``runner/telemetry.py`` carries the canonical name inventory as a
``REGISTRY`` literal; TEL002 reads it via ``ast.literal_eval`` — the
linter never imports the package. Registry entries may use ``*``
wildcards for parameterized families (``phase:*``,
``stream.*_reuse``).

- TEL001 — span created but not used as a ``with`` context (and not
  stored/returned for the caller to enter): enter/exit imbalance,
  the span is a silent no-op.
- TEL002 — span/counter/event name (or its constant f-string prefix)
  that matches nothing in the registry.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Iterator, Optional, Tuple

FAMILY = "TEL"

RULES = {
    "TEL001": "span created but never entered (no with-context)",
    "TEL002": "telemetry name not in the runner/telemetry.py REGISTRY",
}

_KIND = {"span": "spans", "counter": "counters", "event": "events",
         "hist": "hists", "hist_many": "hists"}


def _name_arg(node: ast.Call) -> Tuple[Optional[str], bool]:
    """(name-or-prefix, is_prefix) from the first positional arg;
    (None, False) when it isn't string-shaped (e.g. re.Match.span(1))."""
    if not node.args:
        return None, False
    a = node.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        return a.value, False
    if isinstance(a, ast.JoinedStr) and a.values \
            and isinstance(a.values[0], ast.Constant) \
            and isinstance(a.values[0].value, str):
        return a.values[0].value, True
    if isinstance(a, ast.BinOp) and isinstance(a.op, ast.Add) \
            and isinstance(a.left, ast.Constant) \
            and isinstance(a.left.value, str):
        return a.left.value, True
    return None, False


def _registered(name: str, is_prefix: bool, entries) -> bool:
    if not is_prefix:
        return any(fnmatch.fnmatchcase(name, e) for e in entries)
    for e in entries:
        head = e.split("*", 1)[0]
        if name.startswith(head) or head.startswith(name):
            return True
    return False


def check(module, ctx) -> Iterator:
    if ctx.policy.registry_module(module.relpath):
        return  # the registry module defines the API; don't self-lint
    registry = ctx.policy.tel_registry
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute) \
                or node.func.attr not in _KIND:
            continue
        name, is_prefix = _name_arg(node)
        if name is None:
            continue  # not the telemetry signature (re.Match.span etc)

        if node.func.attr == "span":
            parent = module.parent(node)
            entered = isinstance(parent, ast.withitem) or \
                isinstance(parent, (ast.Assign, ast.AnnAssign,
                                    ast.Return, ast.NamedExpr))
            if not entered:
                yield module.finding(
                    "TEL001", node,
                    f"span {name!r} is created but never entered; use "
                    "`with tel.span(...):` (or store/return it for the "
                    "caller to enter)")

        if registry is not None:
            entries = registry.get(_KIND[node.func.attr], ())
            if not _registered(name, is_prefix, entries):
                what = "prefix" if is_prefix else "name"
                yield module.finding(
                    "TEL002", node,
                    f"{node.func.attr} {what} {name!r} is not in the "
                    "runner/telemetry.py REGISTRY; dashboards join by "
                    "name — register it or fix the typo")
