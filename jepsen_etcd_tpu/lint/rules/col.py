"""COL: columnar discipline.

Direction 1 deleted the per-op dict round-trip from the hot checker
paths; the tier-1 guard ``History.dict_materializations == 0`` catches
a regression only when a test happens to drive the offending path over
a column-only history. COL is the static twin: in modules declared
columnar (policy.COLUMNAR — ops/ and the columnar checkers), touching
the dict-op surface of a History is a finding even if every current
test keeps its histories dict-backed.

- COL001 — materializing dict ops: ``.ops`` / ``.to_ops()`` /
  ``.op_at()``.
- COL002 — dict-backed History APIs (filter/pairing helpers): each one
  walks ``self.ops`` internally, so the materialization is just hidden
  one call deeper.

Guarded fallbacks (``if columns is None: <dict path>``) are the
documented escape hatch — suppress them in place with the reason.
"""

from __future__ import annotations

import ast
from typing import Iterator

FAMILY = "COL"

RULES = {
    "COL001": "dict-op materialization in a columnar module",
    "COL002": "dict-backed History API in a columnar module",
}

_MATERIALIZE_CALLS = {"to_ops", "op_at"}
_DICT_APIS = {"client_ops", "nemesis_ops", "oks", "invokes", "remove_f",
              "filter", "completion", "invocation", "by_index", "pairs"}
#: attribute names whose ``.ops`` access is NOT History.ops
_ATTR_FALSE_FRIENDS = {"self"}


def check(module, ctx) -> Iterator:
    if not ctx.policy.columnar(module.relpath):
        return
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Attribute):
            parent = module.parent(node)
            if node.attr == "ops" and not (
                    isinstance(node.value, ast.Name)
                    and node.value.id in _ATTR_FALSE_FRIENDS):
                yield module.finding(
                    "COL001", node,
                    ".ops materializes one dict per op "
                    "(History.dict_materializations); consume the SoA "
                    "columns instead")
            elif node.attr in _MATERIALIZE_CALLS and \
                    isinstance(parent, ast.Call) and parent.func is node:
                yield module.finding(
                    "COL001", node,
                    f".{node.attr}() materializes dict ops; consume "
                    "the SoA columns instead")
            elif node.attr in _DICT_APIS and (
                    (isinstance(parent, ast.Call) and parent.func is node)
                    or node.attr == "pairs"):
                yield module.finding(
                    "COL002", node,
                    f"History.{node.attr} walks the dict op list "
                    "internally; use the columnar accessors "
                    "(client_pairs, split_by_key, typed arrays)")
