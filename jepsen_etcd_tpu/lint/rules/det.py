"""DET: determinism rules.

The whole correctness story of this port rests on bit-identical
replays: same seed, same history, same verdict (the 9-config
golden-hash bar, PERF.md §gen). These rules prove the three classic
leak paths are closed at parse time:

- DET001 — wall-clock reads reachable from sim/verdict code. Virtual
  time is the only clock the deterministic core may observe; the
  WallLoop/telemetry allowlist (policy.DET_WALLCLOCK_ALLOW) carries
  the modules that measure *host* cost by design.
- DET002 — unseeded module-level randomness. Every random draw must
  come through a seeded ``random.Random`` / ``np.random.default_rng``
  instance (the SimLoop owns one); ``random.random()`` or
  ``np.random.rand()`` silently forks the history from its seed.
- DET003 — hash/id-ordered iteration escaping: iterating a set (or
  coercing one to a sequence) without ``sorted``, and ``id()`` used as
  a key — str hashes are randomized per process, id() is allocation
  order; both leak arbitrary order into histories or verdicts.
"""

from __future__ import annotations

import ast
from typing import Iterator

FAMILY = "DET"

RULES = {
    "DET001": "wall-clock call reachable from sim/verdict code",
    "DET002": "unseeded module-level randomness",
    "DET003": "hash- or id-ordered data escaping into results",
}

#: dotted origins that read the wall clock
_WALL_CLOCK = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

#: module-level random draws (the seeded-instance API is fine)
_RANDOM_MODULES = ("random", "numpy.random")
_RANDOM_OK = {"Random", "SystemRandom", "default_rng", "Generator",
              "RandomState", "seed"}

_SEQ_COERCE = {"list", "tuple", "iter", "enumerate"}


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("set", "frozenset"):
        return True
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
        return _is_set_expr(node.left) or _is_set_expr(node.right)
    return False


def check(module, ctx) -> Iterator:
    policy = ctx.policy
    wallclock_ok = policy.wallclock_allowed(module.relpath)
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            origin = module.origin(node.func)
            # DET001 wall clock
            if origin in _WALL_CLOCK and not wallclock_ok \
                    and ctx.reachable(module, node):
                yield module.finding(
                    "DET001", node,
                    f"wall-clock call {origin}() reachable from "
                    "sim/verdict code; use the loop's virtual clock, or "
                    "move host-cost timing behind the telemetry "
                    "allowlist")
            # DET002 unseeded randomness (anywhere in the package —
            # there is no benign place for an unseeded draw)
            if origin is not None:
                head, _, leaf = origin.rpartition(".")
                if head in _RANDOM_MODULES and leaf not in _RANDOM_OK:
                    yield module.finding(
                        "DET002", node,
                        f"module-level {origin}() draws from unseeded "
                        "global state; use a seeded Random/Generator "
                        "instance (the SimLoop owns loop.rng)")
            # DET003b id() as a key
            if isinstance(node.func, ast.Name) and node.func.id == "id" \
                    and len(node.args) == 1 \
                    and ctx.reachable(module, node):
                yield module.finding(
                    "DET003", node,
                    "id() is allocation order and can alias after GC; "
                    "key on a stable identity instead")
            # DET003a sequence coercion of a set
            if isinstance(node.func, ast.Name) \
                    and node.func.id in _SEQ_COERCE and node.args \
                    and _is_set_expr(node.args[0]) \
                    and ctx.reachable(module, node):
                yield module.finding(
                    "DET003", node,
                    f"{node.func.id}() over a set fixes an arbitrary "
                    "hash order; wrap in sorted() if the order can "
                    "reach a history or verdict")
        elif isinstance(node, (ast.For, ast.AsyncFor)) \
                and _is_set_expr(node.iter) \
                and ctx.reachable(module, node):
            yield module.finding(
                "DET003", node,
                "iterating a set in hash order; wrap in sorted() if "
                "the order can reach a history or verdict")
        elif isinstance(node, ast.comprehension) \
                and _is_set_expr(node.iter) \
                and ctx.reachable(module, node.iter):
            yield module.finding(
                "DET003", node.iter,
                "comprehension over a set in hash order; wrap in "
                "sorted() if the order can reach a history or verdict")
