"""THR: thread-safety discipline.

The deterministic core is single-threaded by construction, but two
real thread boundaries exist: the StreamFeed worker (checker thread
overlapping generation) and the wall-time bridge / live-client reader
threads. The invariant on those surfaces is: shared mutable state
crosses a thread boundary only under a Lock/Condition or through a
Queue/Event. A bare ``self.x = ...`` from a worker races with the
main loop's read — exactly the withdrawal race class the stream
finalize handshake guards against.

Scope: only modules that actually construct ``threading.Thread``.
Worker code = the ``target=`` functions plus everything they call by
simple name inside the same module.

- THR001 — write to a shared ``self.*`` attribute from worker code
  with no enclosing lock ``with`` block, when the attribute is also
  touched outside the worker (the shared surface).
- THR002 — ``global`` rebinding inside worker code: module globals
  have no lock at all.
"""

from __future__ import annotations

import ast
from typing import Iterator

FAMILY = "THR"

RULES = {
    "THR001": "unsynchronized shared-attribute write from a worker "
              "thread",
    "THR002": "module-global rebinding from a worker thread",
}

_LOCKISH = ("lock", "cond", "cv", "mutex")


def _worker_entry_names(tree: ast.AST) -> set:
    """Simple names handed to ``threading.Thread(target=...)``."""
    out: set = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        leaf = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if leaf != "Thread":
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            v = kw.value
            if isinstance(v, ast.Name):
                out.add(v.id)
            elif isinstance(v, ast.Attribute):
                out.add(v.attr)
    return out


def _called_names(fn: ast.AST) -> set:
    """Bare-name and ``self.x()`` calls only: ``other.finish()`` must
    not pull an unrelated same-named method into the worker set."""
    out: set = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                out.add(f.id)
            elif isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "self":
                out.add(f.attr)
    return out


def _worker_functions(module, entries: set) -> list:
    """Defs reachable from the Thread targets by simple name within
    this module (over-approximate: name match, any class)."""
    defs = [n for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    by_name: dict = {}
    for d in defs:
        by_name.setdefault(d.name, []).append(d)
    frontier = list(entries)
    seen: set = set()
    workers = []
    while frontier:
        name = frontier.pop()
        if name in seen:
            continue
        seen.add(name)
        for d in by_name.get(name, ()):
            workers.append(d)
            frontier.extend(_called_names(d) - seen)
    return workers


def _under_lock(module, node: ast.AST) -> bool:
    """Any enclosing ``with`` whose context expression names something
    lock-like (lock/cond/cv/mutex) — the Condition/Lock discipline."""
    cur = module.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                txt = ast.unparse(item.context_expr).lower()
                if any(k in txt for k in _LOCKISH):
                    return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
        cur = module.parent(cur)
    return False


def _self_attr_targets(stmt: ast.AST):
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        for node in ast.walk(t):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self":
                yield node


def check(module, ctx) -> Iterator:
    entries = _worker_entry_names(module.tree)
    if ctx.policy.all_in_scope and not entries:
        # fixtures may name the worker conventionally
        entries = {"_worker", "worker", "run"} & {
            n.name for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    if not entries:
        return
    workers = _worker_functions(module, entries)
    worker_nodes = set()
    for w in workers:
        for n in ast.walk(w):
            worker_nodes.add(n)

    # the shared surface: self-attrs touched OUTSIDE worker code too
    outside_attrs: set = set()
    for node in ast.walk(module.tree):
        if node in worker_nodes:
            continue
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            outside_attrs.add(node.attr)

    for w in workers:
        for stmt in ast.walk(w):
            if isinstance(stmt, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                for attr_node in _self_attr_targets(stmt):
                    if attr_node.attr not in outside_attrs:
                        continue
                    if _under_lock(module, stmt):
                        continue
                    yield module.finding(
                        "THR001", stmt,
                        f"self.{attr_node.attr} is written from the "
                        f"worker thread ({w.name}) without a lock but "
                        "is also touched from the main thread; hold "
                        "the Condition/Lock or hand the value over a "
                        "Queue")
            elif isinstance(stmt, ast.Global):
                yield module.finding(
                    "THR002", stmt,
                    f"worker thread ({w.name}) rebinds module "
                    f"global(s) {', '.join(stmt.names)}; globals have "
                    "no lock — use an instance attribute under the "
                    "worker's Condition")
