"""JAX: dispatch hygiene.

PERF.md §1's cost model: ~57 ms fixed cost per un-jitted launch and a
~100 ms tunnel round trip — one stray per-iteration dispatch inside a
Python loop turns a single-kernel checker into a launch storm, and a
jit built per call retraces per shape (the retrace-storm hazard the
bucketed-pad discipline exists to prevent). These hazards only surface
dynamically in BENCH rounds on a device; statically they are visible
in the AST.

- JAX001 — per-iteration ``jnp.*``/``lax.*`` dispatch inside a Python
  ``for``/``while`` body in a function that is not device-traced
  (jitted, or passed to ``lax``/pallas control flow). Inside a traced
  function the loop unrolls at trace time and is fine.
- JAX002 — host transfer (``np.asarray``/``np.array``/
  ``jax.device_get``/``.block_until_ready()``) inside a loop in a
  non-traced function: a device sync per iteration.
- JAX003 — ``jax.jit`` created inside a loop or per call without a
  cache: every call builds (and possibly retraces) a fresh callable;
  jits belong at module level or behind ``lru_cache`` keyed on the
  bucketed shape.
- JAX004 — explicit float64 on the device path: TPUs emulate f64 at a
  large multiple; the kernels here are int32/uint32 by design, so any
  ``jnp`` float64 is either an accident or a silent-promotion hazard.
"""

from __future__ import annotations

import ast
from typing import Iterator

FAMILY = "JAX"

RULES = {
    "JAX001": "per-iteration jnp dispatch in a non-traced Python loop",
    "JAX002": "host-device transfer inside a Python loop",
    "JAX003": "jit built per call/iteration (retrace hazard)",
    "JAX004": "explicit float64 on the device path",
}

_JNP_ROOTS = {"jax.numpy", "jax.lax", "jax"}
_TRANSFER_ORIGINS = {"numpy.asarray", "numpy.array", "jax.device_get"}
_TRACED_CONSUMERS = {"while_loop", "scan", "fori_loop", "cond", "switch",
                     "pallas_call", "jit", "vmap", "pmap", "shard_map"}
_CACHE_DECOS = {"lru_cache", "cache"}


def _jit_decorated(fn: ast.AST) -> bool:
    for deco in getattr(fn, "decorator_list", ()):
        for n in ast.walk(deco):
            if (isinstance(n, ast.Name) and "jit" in n.id) or \
                    (isinstance(n, ast.Attribute) and "jit" in n.attr):
                return True
    return False


def _cache_decorated(fn: ast.AST) -> bool:
    for deco in getattr(fn, "decorator_list", ()):
        for n in ast.walk(deco):
            if (isinstance(n, ast.Name) and n.id in _CACHE_DECOS) or \
                    (isinstance(n, ast.Attribute) and
                     n.attr in _CACHE_DECOS):
                return True
    return False


def _traced_names(module) -> set:
    """Names of functions that run at trace time: jit-decorated, handed
    to jit / lax control flow / pallas, or (transitively) called from
    one of those — a kernel helper called from a pallas body traces
    with it."""
    out: set = set()
    by_name: dict = {}
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
            if _jit_decorated(node):
                out.add(node.name)
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        leaf = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        if leaf in _TRACED_CONSUMERS:
            # any name in the argument subtree: covers both the direct
            # form jit(run) and the factory form
            # pallas_call(_make_kernel(...))
            for arg in list(node.args) + [k.value for k in node.keywords]:
                for n in ast.walk(arg):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
    # fixpoint: a traced def's bare-name calls trace with it, and so
    # does an inner def it returns (kernel-factory pattern)
    frontier = list(out)
    while frontier:
        name = frontier.pop()
        for d in by_name.get(name, ()):
            fresh = set()
            for n in ast.walk(d):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Name):
                    fresh.add(n.func.id)
                elif isinstance(n, ast.Return) \
                        and isinstance(n.value, ast.Name):
                    fresh.add(n.value.id)
            for f_name in fresh - out:
                out.add(f_name)
                frontier.append(f_name)
    return out


def _is_traced(module, node: ast.AST, traced: set) -> bool:
    """Any enclosing def jitted, cache-built, or passed to a traced
    consumer ⇒ this code runs at trace time, not per host iteration."""
    for fn in module.enclosing_functions(node):
        if _jit_decorated(fn) or fn.name in traced:
            return True
    return False


def _origin_head(module, node: ast.AST):
    origin = module.origin(node)
    if origin is None:
        return None, None
    head, _, leaf = origin.rpartition(".")
    return origin, head


def check(module, ctx) -> Iterator:
    traced = _traced_names(module)
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        origin = module.origin(node.func)
        in_loop = bool(module.enclosing_loops(node))
        traced_here = _is_traced(module, node, traced)
        leaf = node.func.attr if isinstance(node.func, ast.Attribute) \
            else (node.func.id if isinstance(node.func, ast.Name)
                  else None)

        # JAX001: jnp dispatch per host-loop iteration
        if origin is not None and in_loop and not traced_here:
            head = origin.rpartition(".")[0]
            if head in _JNP_ROOTS or head.startswith("jax.numpy") \
                    or head.startswith("jax.lax"):
                yield module.finding(
                    "JAX001", node,
                    f"{origin}() dispatches per loop iteration outside "
                    "jit (~57 ms/launch fixed cost, PERF.md §1); batch "
                    "the loop or move it under a traced control-flow "
                    "primitive")

        # JAX002: host transfer per loop iteration
        if in_loop and not traced_here:
            if origin in _TRANSFER_ORIGINS or \
                    (leaf == "block_until_ready" and not node.args):
                yield module.finding(
                    "JAX002", node,
                    "host-device transfer inside a Python loop forces "
                    "a sync per iteration; hoist the transfer or batch "
                    "the loop")

        # JAX003: jit built per call / per iteration
        if leaf == "jit" or (origin is not None
                             and origin.endswith(".jit")):
            encl = module.enclosing_functions(node)
            cached = any(_cache_decorated(f) for f in encl)
            if in_loop or (encl and not cached and not traced_here):
                yield module.finding(
                    "JAX003", node,
                    "jit built per call retraces per shape; build it "
                    "at module level or behind lru_cache keyed on the "
                    "bucketed shape")

        # JAX004: explicit float64 on the device path
        if origin is not None and (
                origin.rpartition(".")[0] in _JNP_ROOTS
                or origin.startswith("jax.numpy")):
            for kw in node.keywords:
                if kw.arg == "dtype" and _mentions_f64(module, kw.value):
                    yield module.finding(
                        "JAX004", node,
                        "explicit float64 on the device path; the "
                        "kernels are int32/uint32 by design and TPUs "
                        "emulate f64")
        if leaf == "astype" and node.args \
                and _mentions_f64(module, node.args[0]):
            yield module.finding(
                "JAX004", node,
                "astype(float64): silent f64 promotion hazard on the "
                "device path")


def _mentions_f64(module, node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and n.value == "float64":
            return True
        if isinstance(n, ast.Attribute) and n.attr == "float64":
            origin = module.origin(n)
            # numpy.float64 on a *host* array is fine; only the jnp
            # alias (or an unresolvable root) is the device hazard
            if origin is None or not origin.startswith("numpy."):
                return True
        if isinstance(n, ast.Name) and n.id == "float":
            return True
    return False
