"""Rule registry: five families, each a module with ``FAMILY``,
``RULES`` (id -> one-line description) and ``check(module, ctx)``."""

from __future__ import annotations

from typing import Iterable, Optional

from . import col, det, jax_rules, tel, thr

FAMILIES = (det, col, jax_rules, thr, tel)

ALL_RULES = {rid: desc for fam in FAMILIES
             for rid, desc in fam.RULES.items()}


def select(rules: Optional[Iterable[str]] = None) -> list:
    """Rule-family modules matching the requested families/ids
    (None = all). Unknown selectors raise — a typo'd --rule must not
    silently lint nothing."""
    if not rules:
        return list(FAMILIES)
    want = {r.upper() for r in rules}
    unknown = {w for w in want
               if w not in ALL_RULES
               and w not in {f.FAMILY for f in FAMILIES}}
    if unknown:
        raise ValueError(f"unknown rules {sorted(unknown)}; known "
                         f"families {sorted(f.FAMILY for f in FAMILIES)}")
    return [f for f in FAMILIES
            if f.FAMILY in want or any(r in want for r in f.RULES)]
