"""Path-scoped lint policy: which invariant applies where.

The rules are generic AST analyses; this module pins them to THIS
repo's architecture — which modules are declared columnar (the static
twin of the ``History.dict_materializations == 0`` runtime guard),
which modules legitimately read the wall clock (the WallLoop/telemetry
allowlist), where the reachability roots of the deterministic core
are, and which files are out of scope entirely (generated protobufs,
the linter itself).

Tests construct a permissive ``Policy(all_in_scope=True)`` so fixture
snippets exercise every rule without path gymnastics.
"""

from __future__ import annotations

import fnmatch
from typing import Iterable, Optional

#: files never scanned: generated code and the linter's own tree
EXCLUDE = (
    "client/proto/*",
    "lint/*",
)

#: modules declared columnar: dict-op APIs (History.ops, to_ops, op_at,
#: the filter/pairing helpers) are violations here, not style — these
#: are exactly the paths the dict_materializations==0 tier-1 guard
#: protects dynamically (ROADMAP direction 1)
COLUMNAR = (
    "ops/*",
    "checkers/set_full.py",
    "checkers/perf.py",
    "checkers/timeline.py",
    "checkers/tpu_linearizable.py",
    "checkers/session.py",
    "checkers/mvcc.py",
    "core/mvcc.py",     # the MVCC model builds from OpColumns in one
                        # pass; its dict-stream fallback is the single
                        # declared ignore in history_columns
    "simbatch/*",       # the batched generator BIRTHS histories as
                        # columns; materializing dicts inside it would
                        # defeat the subsystem (history_sha's to_jsonl
                        # is the declared test/bench-only exception)
)

#: modules allowed to read the wall clock: the wall-time bridge itself,
#: host-cost telemetry (spans measure host seconds by design), the
#: run-phase timers feeding those counters, real-process management
#: (readiness backoff against live etcd), and operator tooling that
#: never touches a verdict
DET_WALLCLOCK_ALLOW = (
    "runner/wall.py",
    "runner/telemetry.py",
    "runner/trace.py",
    "runner/test_runner.py",
    "runner/store.py",
    "runner/store_index.py",     # index rows carry artifact mtimes
                                 # (stat-based, never time.time) for
                                 # dashboard ordering only — verdicts
                                 # never read the index
    "runner/campaign.py",        # pool orchestration: wall-clock is
                                 # sweep accounting, never verdict
                                 # input (verdicts come from workers'
                                 # run_test)
    "runner/checker_service.py",  # socket I/O + coalescing-tick
                                  # timing; the device verdicts it
                                  # returns are pure functions of the
                                  # shipped packs (THR still applies
                                  # to its reader/dispatcher threads)
    "runner/transport.py",        # framed-socket plumbing (connect
                                  # timeouts, preambles): pure
                                  # transport, never verdict input
    "runner/host_agent.py",       # worker-agent supervision: spawn/
                                  # heartbeat/requeue timing (THR
                                  # still applies to its drive and
                                  # beat threads)
    "runner/guided.py",          # campaign-wave orchestration: wall
                                 # time is summary accounting only
                                 # (scores come from coverage vectors,
                                 # never the clock)
    "runner/shrink.py",          # artifact mtimes/summary wall only;
                                 # acceptance is signature equality on
                                 # replayed deterministic histories
    "runner/stream.py",          # streaming/fused-pipeline telemetry:
                                 # chunk-lag stamps and gen/check busy
                                 # walls are host accounting only —
                                 # verdicts come from the bit-identical
                                 # pack + ladder reuse paths, never the
                                 # clock
    "db/local.py",
    "db/fake_etcd.py",
    "net/*",            # userspace proxy plane: socket splice loops
                        # sleep real seconds to inject latency and
                        # bandwidth caps — transport I/O by design,
                        # never verdict input (the checker only ever
                        # sees the resulting history timestamps from
                        # WallLoop)
    "sut/*",            # gateway bridges: readiness deadlines against
                        # live sockets/processes, never verdict input
    "client/etcdctl.py",
    "serve.py",
    "cli.py",
    "forensics.py",
)

#: reachability roots for DET scoping: the deterministic kernel's run
#: loop, the generator interpreter, and every checker verdict entry.
#: Matched against callgraph qualnames (module:Class.func) by suffix.
ENTRY_SUFFIXES = (
    "SimLoop.run",
    ":interpret",
    ".check",
    ".check_batch",
)

#: relpath of the module whose REGISTRY assignment is the canonical
#: telemetry name source (TEL002 reads it via ast.literal_eval — the
#: linter never imports the package)
TEL_REGISTRY_MODULE = "runner/telemetry.py"


def _match(rel: str, patterns: Iterable[str]) -> bool:
    return any(fnmatch.fnmatch(rel, p) for p in patterns)


class Policy:
    """Scope decisions for one lint run.

    ``all_in_scope=True`` (fixture tests) makes every file columnar,
    THR-scoped, and entry-reachable, with an empty wall-clock
    allowlist — every rule can fire on a bare snippet.
    """

    def __init__(self, all_in_scope: bool = False,
                 tel_registry: Optional[dict] = None):
        self.all_in_scope = all_in_scope
        #: {"span": [...], "counter": [...], "event": [...]} with
        #: ``*`` wildcards; None means "not loaded" (TEL002 skipped)
        self.tel_registry = tel_registry

    def excluded(self, rel: str) -> bool:
        if self.all_in_scope:
            return False
        return _match(rel, EXCLUDE)

    def columnar(self, rel: str) -> bool:
        return self.all_in_scope or _match(rel, COLUMNAR)

    def wallclock_allowed(self, rel: str) -> bool:
        if self.all_in_scope:
            return False
        return _match(rel, DET_WALLCLOCK_ALLOW)

    def entry_point(self, qualname: str) -> bool:
        """Is this def a reachability root? qualname: module:Class.func."""
        if self.all_in_scope:
            return True
        return any(qualname.endswith(s) for s in ENTRY_SUFFIXES)

    def registry_module(self, rel: str) -> bool:
        return (not self.all_in_scope) and rel == TEL_REGISTRY_MODULE
