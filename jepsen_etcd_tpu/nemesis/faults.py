"""The full fault suite: kill, pause, partition, clock, member, corrupt,
admin — composed packages (the nemesis.clj + jepsen.nemesis.combined
analog).

Each package is {fs, nemesis, generator, final_generator, perf}; packages
compose by routing ops on ``f`` (nc/compose-packages). Target specs
mirror the reference's configuration (etcd.clj:105-112): kill/pause
target ``primaries``/``all``; partitions target ``primaries`` /
``majority`` / ``majorities-ring``. Corruption targets only the first
``majority(n) - 1`` nodes so a quorum stays intact (nemesis.clj:176);
bitflip probability ∈ {1e-3, 1e-4, 1e-5} and truncation drops ≤1024
bytes (nemesis.clj:182-183). Admin ops compact at a random client and
defrag random subsets (nemesis.clj:72-143). Every package heals in its
final generator: restart everything, resume, drop partitions, reset
clocks, grow the cluster back (capped at 60 s), compact+defrag.
"""

from __future__ import annotations

from typing import Any, Optional

from ..core.op import Op
from ..generators import (fn_gen, limit, mix, stagger, delay, time_limit,
                          phases, any_gen, seq)
from ..runner.sim import current_loop, sleep, SECOND
from ..sut.errors import SimError
from .packages import Nemesis

MS = 1_000_000


def _majority(n: int) -> int:
    return n // 2 + 1


async def _resolve_targets(test: dict, spec: str) -> list[str]:
    """Resolve a target spec to node names at invoke time."""
    db = test["db"]
    members = sorted(db.members or test["nodes"])
    if spec == "all":
        return members
    if spec == "one":
        return [current_loop().rng.choice(members)]
    if spec == "minority":
        rng = current_loop().rng
        picks = rng.sample(members, max(1, _majority(len(members)) - 1))
        return sorted(picks)
    if spec == "primaries":
        return await db.primaries(test)
    raise ValueError(f"unknown target spec {spec!r}")


class _FnNemesis(Nemesis):
    """Dispatch table f -> async handler(test, op)."""

    def __init__(self, handlers: dict):
        self.handlers = handlers

    @property
    def fs(self) -> set:
        return set(self.handlers)

    async def invoke(self, test: dict, op: Op) -> Op:
        return await self.handlers[op.f](test, op)


class ComposedNemesis(Nemesis):
    def __init__(self, parts: list[Nemesis]):
        self.parts = parts

    @property
    def fs(self) -> set:
        out: set = set()
        for p in self.parts:
            out |= p.fs
        return out

    async def setup(self, test: dict) -> None:
        for p in self.parts:
            await p.setup(test)

    async def invoke(self, test: dict, op: Op) -> Op:
        for p in self.parts:
            if op.f in p.fs:
                return await p.invoke(test, op)
        raise ValueError(f"no nemesis handles f={op.f!r}")

    async def teardown(self, test: dict) -> None:
        for p in self.parts:
            await p.teardown(test)


# ---- kill / pause ----------------------------------------------------------

def _process_package(kind: str, opts: dict, targets: list[str]) -> dict:
    """kill/start or pause/resume package (jepsen.nemesis.combined db/
    pause packages, wired at etcd.clj:105-112)."""
    interval = int(opts.get("nemesis_interval", 5) * SECOND)
    stop_f, start_f = (("kill", "start") if kind == "kill"
                       else ("pause", "resume"))

    async def do_stop(test, op):
        nodes = await _resolve_targets(test, op.value or "all")
        db = test["db"]
        out = {}
        for n in nodes:
            out[n] = (db.kill(test, n) if kind == "kill"
                      else db.pause(test, n))
        return op.evolve(type="info", value=out)

    async def do_start(test, op):
        db = test["db"]
        out = {}
        for n in sorted(db.members or test["nodes"]):
            out[n] = (db.start(test, n) if kind == "kill"
                      else db.resume(test, n))
        return op.evolve(type="info", value=out)

    def gen_stop(test, ctx):
        return {"f": stop_f, "value": ctx.rng.choice(targets)}

    def gen_start(test, ctx):
        return {"f": start_f, "value": "all"}

    return {
        "fs": {stop_f, start_f},
        "nemesis": _FnNemesis({stop_f: do_stop, start_f: do_start}),
        "generator": stagger(interval, mix([gen_stop, gen_start])),
        "final_generator": limit(1, fn_gen(gen_start)),
        "perf": [{"name": kind, "fs": [stop_f, start_f],
                  "start": [stop_f], "stop": [start_f],
                  "color": "#E9A4A0" if kind == "kill" else "#A0B2E9"}],
    }


# ---- partition / latency ---------------------------------------------------

def _partition_pool(test: dict) -> list[str]:
    """Nodes a network fault can target: alive sim nodes, or the local
    control plane's current membership."""
    cluster = test.get("cluster")
    if cluster is not None:
        nodes = sorted(cluster.nodes)
        alive = [n for n in nodes if cluster.nodes[n].alive]
        return alive or nodes
    db = test["db"]
    return sorted(db.members or test["nodes"])


def _net_backend(test: dict):
    """The network fault surface: the simulated Cluster, or the local
    control plane's userspace proxy fleet (net/plane.py). Both speak
    partition/partition_pairs/heal_partition/set_latency/clear_latency
    with the shared blocked-pair encoding."""
    cluster = test.get("cluster")
    if cluster is not None:
        return cluster
    plane = getattr(test.get("db"), "plane", None)
    if plane is None:
        raise SimError(
            "unsupported",
            "no network fault backend: need the sim cluster or "
            "--db local with the net proxy plane (--net-proxy)",
            definite=True)
    return plane


def _partition_groups(test: dict, spec: str, primaries: list) -> Any:
    """Compute a partition. Returns either a list of groups (disjoint
    isolation) or a set of blocked pairs — frozensets are
    bidirectional, ordered (src, dst) tuples are one-way."""
    rng = current_loop().rng
    pool = _partition_pool(test)
    if spec == "primaries" and primaries:
        p = rng.choice(sorted(primaries))
        return [[p], [n for n in pool if n != p]]
    if spec == "majority" or (spec == "primaries" and not primaries):
        sh = list(pool)
        rng.shuffle(sh)
        maj = _majority(len(sh))
        return [sh[:maj], sh[maj:]]
    if spec == "majorities-ring":
        # each node sees itself plus its ring neighbors — everyone has a
        # "majority" view but no two agree (the classic etcd killer)
        sh = list(pool)
        rng.shuffle(sh)
        n = len(sh)
        keep = max(1, (_majority(n) - 1) // 2)
        blocked = set()
        for i in range(n):
            for j in range(i + 1, n):
                dist = min((j - i) % n, (i - j) % n)
                if dist > keep:
                    blocked.add(frozenset((sh[i], sh[j])))
        return blocked
    if spec == "bridge":
        # two halves that only communicate through one bridge node
        # (jepsen.nemesis bridge): neither half has a majority alone,
        # the bridge sees everyone
        sh = list(pool)
        rng.shuffle(sh)
        bridge, rest = sh[0], sh[1:]
        half = len(rest) // 2
        g1, g2 = rest[:half], rest[half:]
        return {frozenset((a, b)) for a in g1 for b in g2}
    if spec == "one-way":
        # asymmetric: one node's OUTBOUND traffic is blackholed while
        # inbound still flows — the fault class a symmetric
        # groups-based partition cannot express
        x = rng.choice(list(pool))
        return {(x, o) for o in pool if o != x}
    raise ValueError(f"unknown partition spec {spec!r}")


def partition_package(opts: dict) -> dict:
    interval = int(opts.get("nemesis_interval", 5) * SECOND)
    targets = ["primaries", "majority", "majorities-ring", "bridge",
               "one-way"]

    async def start(test, op):
        primaries = await test["db"].primaries(test)
        g = _partition_groups(test, op.value, primaries)
        backend = _net_backend(test)
        if isinstance(g, set):
            backend.partition_pairs(g)
            desc = f"{op.value} ({len(g)} blocked links)"
        else:
            backend.partition(g)
            desc = [sorted(x) for x in g]
        return op.evolve(type="info", value=desc)

    async def stop(test, op):
        _net_backend(test).heal_partition()
        return op.evolve(type="info", value="fully-connected")

    def gen_start(test, ctx):
        return {"f": "start-partition", "value": ctx.rng.choice(targets)}

    def gen_stop(test, ctx):
        return {"f": "stop-partition", "value": None}

    return {
        "fs": {"start-partition", "stop-partition"},
        "nemesis": _FnNemesis({"start-partition": start,
                               "stop-partition": stop}),
        "generator": stagger(interval, mix([gen_start, gen_stop])),
        "final_generator": limit(1, fn_gen(gen_stop)),
        "perf": [{"name": "partition",
                  "fs": ["start-partition", "stop-partition"],
                  "start": ["start-partition"],
                  "stop": ["stop-partition"], "color": "#E9DCA0"}],
    }


def latency_package(opts: dict) -> dict:
    """Injected link latency + jitter: the sim adds a bounded extra
    delay to every message leg; local mode programs the proxy plane
    (net/plane.py), which sleeps real milliseconds per chunk."""
    interval = int(opts.get("nemesis_interval", 5) * SECOND)

    async def start(test, op):
        v = op.value or {}
        backend = _net_backend(test)
        backend.set_latency(float(v.get("delta-ms", 50)),
                            float(v.get("jitter-ms", 0)))
        # lossy-link rider: per-chunk drop probability, only the proxy
        # plane speaks it (the sim cluster models loss as timeouts) —
        # guard so the same spec works against either backend
        set_dp = getattr(backend, "set_drop_prob", None)
        if set_dp is not None and v.get("drop-prob"):
            set_dp(float(v["drop-prob"]))
        return op.evolve(type="info")

    async def stop(test, op):
        backend = _net_backend(test)
        backend.clear_latency()
        clear_dp = getattr(backend, "clear_drop_prob", None)
        if clear_dp is not None:
            clear_dp()
        return op.evolve(type="info", value="latency-cleared")

    def gen_start(test, ctx):
        return {"f": "start-latency",
                "value": {"delta-ms": 2 ** ctx.rng.randint(3, 7),
                          "jitter-ms": 2 ** ctx.rng.randint(0, 5),
                          "drop-prob": ctx.rng.choice(
                              [0.0, 0.0, 0.01, 0.05])}}

    def gen_stop(test, ctx):
        return {"f": "stop-latency", "value": None}

    return {
        "fs": {"start-latency", "stop-latency"},
        "nemesis": _FnNemesis({"start-latency": start,
                               "stop-latency": stop}),
        "generator": stagger(interval, mix([gen_start, gen_stop])),
        "final_generator": limit(1, fn_gen(gen_stop)),
        "perf": [{"name": "latency",
                  "fs": ["start-latency", "stop-latency"],
                  "start": ["start-latency"],
                  "stop": ["stop-latency"], "color": "#C9E9A0"}],
    }


# ---- clock -----------------------------------------------------------------

def clock_package(opts: dict) -> dict:
    interval = int(opts.get("nemesis_interval", 5) * SECOND)

    async def bump(test, op):
        cluster = test["cluster"]
        for node, delta in (op.value or {}).items():
            cluster.bump_clock(node, int(delta * MS))
        return op.evolve(type="info")

    async def strobe(test, op):
        # genuinely oscillate: flip each strobed node's clock between 0
        # and +delta every period-ms for duration-ms (the sim analog of
        # jepsen.nemesis.time strobe-time!), so lease-expiry races see a
        # moving clock, not just a one-shot skew
        cluster = test["cluster"]
        v = op.value or {}
        nodes = v.get("nodes", [])
        period = max(1, int(v.get("period-ms", 1))) * MS
        duration = int(v.get("duration-ms", 1000)) * MS
        delta = int(v.get("delta-ms", 200)) * MS
        loop = current_loop()
        end = loop.now + duration
        up = False
        while loop.now < end:
            for node in nodes:
                cluster.bump_clock(node, -delta if up else delta)
            up = not up
            await sleep(min(period, end - loop.now))
        if up:  # land back where we started, residual skew = 0
            for node in nodes:
                cluster.bump_clock(node, -delta)
        return op.evolve(type="info")

    async def reset(test, op):
        cluster = test["cluster"]
        for node in sorted(cluster.nodes):
            cluster.nodes[node].clock_offset = 0
        return op.evolve(type="info", value=sorted(cluster.nodes))

    def rand_subset(ctx, test):
        nodes = sorted(test["cluster"].nodes)
        k = ctx.rng.randint(1, len(nodes))
        return ctx.rng.sample(nodes, k)

    def gen_bump(test, ctx):
        delta = ctx.rng.choice([-1, 1]) * (2 ** ctx.rng.randint(4, 15))
        return {"f": "bump-clock",
                "value": {n: delta for n in rand_subset(ctx, test)}}

    def gen_strobe(test, ctx):
        return {"f": "strobe-clock",
                "value": {"nodes": rand_subset(ctx, test),
                          "period-ms": 2 ** ctx.rng.randint(0, 10),
                          "delta-ms": 2 ** ctx.rng.randint(4, 9),
                          "duration-ms": ctx.rng.randint(200, 2000)}}

    def gen_reset(test, ctx):
        return {"f": "reset-clock", "value": None}

    return {
        "fs": {"bump-clock", "strobe-clock", "reset-clock"},
        "nemesis": _FnNemesis({"bump-clock": bump, "strobe-clock": strobe,
                               "reset-clock": reset}),
        "generator": stagger(interval,
                             mix([gen_bump, gen_strobe, gen_reset])),
        "final_generator": limit(1, fn_gen(gen_reset)),
        "perf": [{"name": "clock",
                  "fs": ["bump-clock", "strobe-clock", "reset-clock"],
                  "color": "#A0E9DC"}],
    }


# ---- membership ------------------------------------------------------------

def member_package(opts: dict) -> dict:
    interval = int(opts.get("nemesis_interval", 5) * SECOND)
    full_count = len(opts["nodes"])

    async def grow(test, op):
        try:
            return op.evolve(type="info",
                             value=await test["db"].grow(test))
        except (SimError, TimeoutError) as e:
            return op.evolve(type="info", value=f"grow-failed: {e}")

    async def shrink(test, op):
        try:
            return op.evolve(type="info",
                             value=await test["db"].shrink(test))
        except (SimError, TimeoutError) as e:
            return op.evolve(type="info", value=f"shrink-failed: {e}")

    def gen(test, ctx):
        return {"f": ctx.rng.choice(["grow", "shrink"]), "value": None}

    def final(test, ctx):
        # until the cluster is back to full strength, emit grows
        # (nemesis.clj:47-64)
        if len(test["db"].members or ()) < full_count:
            return {"f": "grow", "value": None}
        return None

    return {
        "fs": {"grow", "shrink"},
        "nemesis": _FnNemesis({"grow": grow, "shrink": shrink}),
        "generator": stagger(interval, fn_gen(gen)),
        "final_generator": time_limit(60 * SECOND,
                                      delay(1 * SECOND, fn_gen(final))),
        "perf": [{"name": "grow", "fs": ["grow"], "color": "#E9A0E6"},
                 {"name": "shrink", "fs": ["shrink"], "color": "#ACA0E9"}],
    }


# ---- corruption ------------------------------------------------------------

def corrupt_package(opts: dict, faults: set) -> Optional[dict]:
    interval = int(opts.get("nemesis_interval", 5) * SECOND)
    fault_types = []
    if "bitflip-wal" in faults:
        fault_types.append(("bitflip", "wal"))
    if "bitflip-snap" in faults:
        fault_types.append(("bitflip", "snap"))
    if "truncate-wal" in faults:
        fault_types.append(("truncate", "wal"))
    if not fault_types:
        return None

    async def corrupt(test, op):
        (node, spec), = op.value.items()
        test["cluster"].corrupt_file(
            node, which=spec["file"],
            mode="bitflip" if "probability" in spec else "truncate",
            probability=spec.get("probability", 1e-4),
            truncate_bytes=spec.get("drop", 1024))
        return op.evolve(type="info")

    def gen(test, ctx):
        nodes = sorted(test["nodes"])
        targets = nodes[:max(1, _majority(len(nodes)) - 1)]
        node = ctx.rng.choice(targets)
        fault, ftype = ctx.rng.choice(fault_types)
        spec: dict = {"file": ftype}
        if fault == "truncate":
            spec["drop"] = ctx.rng.randint(0, 1024)
        else:
            spec["probability"] = ctx.rng.choice([1e-3, 1e-4, 1e-5])
        return {"f": f"{fault}-{ftype}", "value": {node: spec}}

    fs = {f"{f}-{t}" for f, t in fault_types}
    return {
        "fs": fs,
        "nemesis": _FnNemesis({f: corrupt for f in fs}),
        "generator": stagger(interval, fn_gen(gen)),
        "final_generator": None,
        "perf": [{"name": "corrupt", "fs": sorted(fs),
                  "color": "#99F2E2"}],
    }


# ---- admin (compact / defrag) ---------------------------------------------

def _admin_nodes(test: dict) -> list[str]:
    """Current cluster membership for admin targeting: the db's member
    set tracks grow/shrink; test['nodes'] is only the starting roster."""
    db = test.get("db")
    members = getattr(db, "members", None) if db is not None else None
    return sorted(members or test["nodes"])


def admin_package(opts: dict) -> dict:
    interval = int(opts.get("nemesis_interval", 5) * SECOND)
    # the client factory dispatches on client_type/db_mode, so admin
    # ops work identically against the simulated cluster and the local
    # control plane's real processes
    from ..client import client as make_client

    async def compact(test, op):
        rng = current_loop().rng
        node = rng.choice(_admin_nodes(test))
        c = make_client(test, node)
        try:
            rev = await c.revision()
            await c.compact(rev, physical=True)
            return op.evolve(type="info", value=f"compacted to {rev}")
        except (SimError, TimeoutError) as e:
            return op.evolve(type="info", value="compact-failed",
                             error=str(e))
        finally:
            c.close()

    async def defrag(test, op):
        out = {}
        for node in op.value or _admin_nodes(test):
            c = make_client(test, node)
            try:
                await c.defrag()
                out[node] = "defragged"
            except (SimError, TimeoutError) as e:
                out[node] = f"defrag-failed: {e}"
            finally:
                c.close()
        return op.evolve(type="info", value=out)

    def gen_compact(test, ctx):
        return {"f": "compact", "value": None}

    def gen_defrag(test, ctx):
        nodes = _admin_nodes(test)
        if ctx.rng.random() < 0.5:
            nodes = ctx.rng.sample(nodes, ctx.rng.randint(1, len(nodes)))
        return {"f": "defrag", "value": sorted(nodes)}

    return {
        "fs": {"compact", "defrag"},
        "nemesis": _FnNemesis({"compact": compact, "defrag": defrag}),
        "generator": stagger(interval, mix([gen_compact, gen_defrag])),
        "final_generator": seq(limit(1, fn_gen(gen_compact)),
                               limit(1, fn_gen(gen_defrag))),
        "perf": [{"name": "compact", "fs": ["compact"], "color": "#2021CC"},
                 {"name": "defrag", "fs": ["defrag"], "color": "#BE20CC"}],
    }


# ---- composition -----------------------------------------------------------

#: every fault name the nemesis layer knows (compose.py's fault matrix
#: and the CLI validate against this)
KNOWN_FAULTS = frozenset({
    "kill", "pause", "partition", "latency", "clock", "member", "admin",
    "bitflip-wal", "bitflip-snap", "truncate-wal"})


def build_packages(opts: dict, faults: set) -> dict:
    """Build and compose the packages for the requested fault set
    (nemesis-package, nemesis.clj:200-209)."""
    packages = []
    if "kill" in faults:
        packages.append(_process_package("kill", opts,
                                         ["primaries", "all"]))
    if "pause" in faults:
        packages.append(_process_package("pause", opts,
                                         ["primaries", "all"]))
    if "partition" in faults:
        packages.append(partition_package(opts))
    if "latency" in faults:
        packages.append(latency_package(opts))
    if "clock" in faults:
        packages.append(clock_package(opts))
    if "member" in faults:
        packages.append(member_package(opts))
    if "admin" in faults:
        packages.append(admin_package(opts))
    cp = corrupt_package(opts, faults)
    if cp is not None:
        packages.append(cp)
    unknown = faults - KNOWN_FAULTS
    if unknown:
        raise ValueError(f"unknown faults {sorted(unknown)}")
    if not packages:
        return {"nemesis": None, "generator": None,
                "final_generator": None, "perf": []}

    gens = [p["generator"] for p in packages if p["generator"] is not None]
    finals = [p["final_generator"] for p in packages
              if p["final_generator"] is not None]
    return {
        "nemesis": ComposedNemesis([p["nemesis"] for p in packages]),
        "generator": any_gen(*gens) if gens else None,
        "final_generator": phases(*finals) if finals else None,
        "perf": [spec for p in packages for spec in p["perf"]],
    }
