"""Placeholder: full fault packages land with the nemesis suite."""


def build_packages(opts, faults):
    raise NotImplementedError(f"nemesis faults {sorted(faults)} not yet implemented")
