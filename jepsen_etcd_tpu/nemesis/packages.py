"""Nemesis packages: composed fault injectors (nemesis.clj analog).

A package is {nemesis, generator, final_generator, perf} (the jepsen
nemesis.combined shape, composed at nemesis.clj:200-209). The full fault
suite (kill/pause/partition/clock/member/corrupt/admin) builds here from
the db and cluster fault APIs.
"""

from __future__ import annotations

from typing import Any, Optional

from ..core.op import Op


class Nemesis:
    """Base nemesis: setup/invoke/teardown against the test's cluster."""

    async def setup(self, test: dict) -> None:
        pass

    async def invoke(self, test: dict, op: Op) -> Op:
        raise NotImplementedError

    async def teardown(self, test: dict) -> None:
        pass


class NoopNemesis(Nemesis):
    async def invoke(self, test, op):
        return op.evolve(type="info")


def nemesis_package(opts: dict) -> dict:
    """Build the composed package for opts['nemesis'] fault names
    (parse-nemesis-spec / special-nemeses analog, etcd.clj:75-88)."""
    faults = set(opts.get("nemesis") or [])
    if not faults or faults == {"none"}:
        return {"nemesis": None, "generator": None,
                "final_generator": None, "perf": []}
    from .faults import build_packages
    return build_packages(opts, faults)
