from .packages import nemesis_package, Nemesis

__all__ = ["nemesis_package", "Nemesis"]
