"""The versioned register model (register.clj:55-96, line-for-line
semantics, re-expressed).

Op values are ``[version, value]`` pairs; version is the etcd key version
*resulting* from an update (derived client-side from prev-kv,
register.clj:31-39) or the version read. A None version matches anything.
"""

from __future__ import annotations

from typing import Any, Optional

from .base import Model, inconsistent


class VersionedRegister(Model):
    __slots__ = ("version", "value")

    def __init__(self, version: int = 0, value: Any = None):
        self.version = version
        self.value = value

    def __getstate__(self):
        return (self.version, self.value)

    def __repr__(self):
        return f"v{self.version}: {self.value}"

    def step(self, op):
        op_version, op_value = op.value if op.value is not None else (None, None)
        version2 = self.version + 1
        if op.f == "write":
            if op_version is not None and op_version != version2:
                return inconsistent(
                    f"can't go from version {self.version} to {op_version}")
            return VersionedRegister(version2, op_value)
        if op.f == "cas":
            v, v2 = op_value
            if op_version is not None and op_version != version2:
                return inconsistent(
                    f"can't go from version {self.version} to {op_version}")
            if self.value != v:
                return inconsistent(
                    f"can't CAS {self.value} from {v} to {v2}")
            return VersionedRegister(version2, v2)
        if op.f == "read":
            if op_version is not None and op_version != self.version:
                return inconsistent(
                    f"can't read version {op_version} from version "
                    f"{self.version}")
            if op_value is not None and op_value != self.value:
                return inconsistent(
                    f"can't read {op_value} from register {self.value}")
            return self
        return inconsistent(f"unknown op {op.f}")
