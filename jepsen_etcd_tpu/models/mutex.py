"""The mutex model (knossos model/mutex, used at lock.clj:244)."""

from __future__ import annotations

from .base import Model, inconsistent


class Mutex(Model):
    __slots__ = ("locked",)

    def __init__(self, locked: bool = False):
        self.locked = locked

    def __getstate__(self):
        return self.locked

    def __repr__(self):
        return "locked" if self.locked else "unlocked"

    def step(self, op):
        if op.f == "acquire":
            if self.locked:
                return inconsistent("cannot acquire a held lock")
            return Mutex(True)
        if op.f == "release":
            if not self.locked:
                return inconsistent("cannot release a free lock")
            return Mutex(False)
        return inconsistent(f"unknown op {op.f}")
