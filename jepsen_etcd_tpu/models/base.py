"""Sequential models for linearizability checking (the knossos.model
protocol the reference relies on via checker/linearizable,
register.clj:110-112, lock.clj:244).

A model is an immutable value with ``step(op) -> Model | Inconsistent``.
Models must be hashable: the search memoizes on (linearized-set, model).
"""

from __future__ import annotations

from typing import Any


class Inconsistent:
    __slots__ = ("msg",)

    def __init__(self, msg: str):
        self.msg = msg

    def __repr__(self):
        return f"<inconsistent: {self.msg}>"


def inconsistent(msg: str) -> Inconsistent:
    return Inconsistent(msg)


class Model:
    def step(self, op) -> "Model | Inconsistent":
        raise NotImplementedError

    # models are value types
    def __eq__(self, other):
        return (type(self) is type(other) and
                self.__getstate__() == other.__getstate__())

    def __hash__(self):
        return hash((type(self).__name__, self.__getstate__()))

    def __getstate__(self):
        raise NotImplementedError
