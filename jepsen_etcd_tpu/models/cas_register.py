"""A plain compare-and-set register (knossos model/cas-register)."""

from __future__ import annotations

from typing import Any

from .base import Model, inconsistent


class CASRegister(Model):
    __slots__ = ("value",)

    def __init__(self, value: Any = None):
        self.value = value

    def __getstate__(self):
        return self.value

    def __repr__(self):
        return f"reg({self.value})"

    def step(self, op):
        if op.f == "write":
            return CASRegister(op.value)
        if op.f == "cas":
            old, new = op.value
            if self.value != old:
                return inconsistent(f"can't CAS {self.value} from {old}")
            return CASRegister(new)
        if op.f == "read":
            if op.value is not None and op.value != self.value:
                return inconsistent(
                    f"can't read {op.value} from {self.value}")
            return self
        return inconsistent(f"unknown op {op.f}")
