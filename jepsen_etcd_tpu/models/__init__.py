from .base import Model, Inconsistent, inconsistent
from .versioned_register import VersionedRegister
from .mutex import Mutex
from .cas_register import CASRegister

__all__ = ["Model", "Inconsistent", "inconsistent", "VersionedRegister",
           "Mutex", "CASRegister"]
