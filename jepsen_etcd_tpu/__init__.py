"""jepsen_etcd_tpu — a TPU-native distributed-systems correctness-testing framework.

A from-scratch re-design of the capabilities of jepsen.etcd (the reference
Clojure harness at /root/reference): concurrent workload generation, fault
injection against an in-process etcd-semantics SUT, concurrent history
recording, and — the TPU-native core — history *checkers* (linearizability
search, transactional cycle detection, set analysis, watch-order
verification) expressed as JAX kernels.

Architecture (see SURVEY.md §7):

- ``core``       history model: ops, invoke/complete pairing, packed tensors
- ``runner``     deterministic virtual-time async runtime + generator interpreter
- ``generators`` pure, seedable generator combinators (mix/reserve/stagger/...)
- ``sut``        simulated etcd cluster: MVCC store, raft-ish replication,
                 leases, locks, watches, membership, WAL byte model
- ``client``     txn AST, error taxonomy, direct + text client backends
- ``workloads``  register / set / append / wr / watch / lock / none
- ``models``     sequential models for linearizability (VersionedRegister, Mutex)
- ``checkers``   checker protocol + stats/perf/timeline/set-full/independent/
                 linearizable (CPU oracle and TPU kernel) / elle / watch
- ``ops``        the JAX/TPU kernels: WGL frontier BFS, boolean-matmul
                 transitive closure, wavefront edit distance
- ``parallel``   mesh/sharding helpers (pjit/shard_map over ICI)
- ``nemesis``    fault-injection packages (kill/pause/partition/clock/member/
                 corrupt/admin)
- ``db``         cluster lifecycle automation against the simulated substrate
"""

__version__ = "0.1.0"
