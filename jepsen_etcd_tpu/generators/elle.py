"""Elle-style transaction generators: list-append and rw-register.

The reference delegates txn generation to the Elle library
(``append.clj:183-185`` calls ``jepsen.tests.cycle.append/test`` with
``{:key-count 3 :max-txn-length 4}``; ``wr.clj:87-92`` the rw-register
variant with ``:wfr-keys true``). This module re-creates those generator
semantics:

- a rotating pool of ``key_count`` active keys; a key retires after
  ``max_writes_per_key`` writes and is replaced by a fresh key, so version
  orders stay short and the inference stays tractable;
- txns are 1..max_txn_length micro-ops ``[f, k, v]``:
  list-append: ``["r", k, None]`` / ``["append", k, v]`` with v unique and
  increasing per key; rw-register: ``["r", k, None]`` / ``["w", k, v]``;
- ``wfr_bias``: with rw-register, a write placed after a read in the same
  txn reuses the read's key with some probability, producing the
  writes-follow-reads patterns the checker's version-order inference
  (wfr-keys) feeds on.

Generators are pure functions of the shared mutable state captured in the
closure, driven through ``fn_gen`` on the deterministic loop's rng.
"""

from __future__ import annotations

from ..generators import fn_gen


class _KeyPool:
    """Rotating active-key pool with per-key unique value counters."""

    def __init__(self, key_count: int, max_writes_per_key: int):
        self.key_count = key_count
        self.max_writes = max_writes_per_key
        self.active = list(range(key_count))
        self.next_key = key_count
        self.written: dict[int, int] = {k: 0 for k in self.active}

    def read_key(self, rng) -> int:
        return rng.choice(self.active)

    def write_key(self, rng) -> tuple:
        """Pick a key and its next unique value; rotate exhausted keys."""
        k = rng.choice(self.active)
        self.written[k] += 1
        v = self.written[k]
        if self.written[k] >= self.max_writes:
            i = self.active.index(k)
            self.active[i] = self.next_key
            self.written[self.next_key] = 0
            self.next_key += 1
        return k, v

    def bump(self, k: int) -> int:
        """Next value for a specific key (wfr same-key writes)."""
        self.written[k] = self.written.get(k, 0) + 1
        return self.written[k]


def list_append_gen(key_count: int = 3, max_txn_length: int = 4,
                    max_writes_per_key: int = 32):
    """Txn generator for the list-append workload (append.clj:183-185)."""
    pool = _KeyPool(key_count, max_writes_per_key)

    def gen(test, ctx):
        rng = ctx.rng
        n = rng.randint(1, max_txn_length)
        txn = []
        for _ in range(n):
            if rng.random() < 0.5:
                txn.append(["r", pool.read_key(rng), None])
            else:
                k, v = pool.write_key(rng)
                txn.append(["append", k, v])
        return {"f": "txn", "value": txn}

    return fn_gen(gen)


def rw_register_gen(key_count: int = 3, max_txn_length: int = 4,
                    max_writes_per_key: int = 32, wfr_bias: float = 0.5):
    """Txn generator for the rw-register workload (wr.clj:87-92)."""
    pool = _KeyPool(key_count, max_writes_per_key)

    def gen(test, ctx):
        rng = ctx.rng
        n = rng.randint(1, max_txn_length)
        txn = []
        read_keys: list = []
        for _ in range(n):
            if rng.random() < 0.5:
                k = pool.read_key(rng)
                txn.append(["r", k, None])
                read_keys.append(k)
            elif read_keys and rng.random() < wfr_bias:
                # writes-follow-reads: overwrite a key this txn read
                k = rng.choice(read_keys)
                txn.append(["w", k, pool.bump(k)])
            else:
                k, v = pool.write_key(rng)
                txn.append(["w", k, v])
        return {"f": "txn", "value": txn}

    return fn_gen(gen)
