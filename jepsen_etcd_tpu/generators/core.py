"""Pure, deterministic, seedable generator combinators.

Re-design of jepsen.generator as observed at the reference call sites
(SURVEY.md §2): ``mix``, ``reserve``, ``limit``, ``stagger``, ``phases``,
``time-limit``, ``nemesis``/``clients`` routing, ``each-thread``, ``sleep``,
``log`` (composition at ``etcd.clj:143-155``, ``register.clj:102-119``,
``set.clj:47``, ``watch.clj:359-379``, ``lock.clj:246,260``).

Protocol (mirrors jepsen.generator.Generator, single-op pipeline):

    gen.op(test, ctx)  -> None                      exhausted
                        | (PENDING, wake, gen')     nothing yet; wake is a
                                                    virtual time to re-poll
                                                    at, or None for "on next
                                                    event"
                        | (op_dict, gen')           op ready; op["time"] is
                                                    its earliest emission time
    gen.update(test, ctx, event) -> gen'            informed of invoke /
                                                    completion events

Generators are immutable: every state change returns a new instance, so the
interpreter can hold, replay, and route speculatively without aliasing bugs.
Every poll the interpreter makes is *committed* (it always adopts gen'),
which lets stateful combinators (stagger, sleep, time-limit, limit) keep
their bookkeeping in the returned copies.

Plain data lifts (ensure_gen):
  dict/Op      -> emit that op once
  callable     -> call f(test, ctx) (or f()) for a fresh op each emission;
                  exhausted when it returns None
  list/iter    -> each element is itself a generator, run in order
  None         -> exhausted
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Iterable, Optional

from ..core.op import Op, NEMESIS

PENDING = "pending"
_arity_cache: dict = {}  # FnGen: f -> parameter count (inspect is hot-loop cost)


class _WorkersMap(dict):
    """Context.workers carrier with a memo of thread-subset dicts.

    The interpreter reuses one snapshot across polls until workers actually
    change, so restrict() (called per combinator level per poll — HOT LOOP
    #1, SURVEY §3.5) can reuse the subset dicts too.  Snapshots are replaced,
    never mutated, so sharing is safe.
    """

    __slots__ = ("sub_cache",)

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.sub_cache: dict = {}
SECOND = 1_000_000_000


# ---------------------------------------------------------------------------
# Context


class Context:
    """What a generator may observe: virtual time, free threads, workers.

    ``workers`` maps thread id -> current process (threads are stable; the
    process on a thread is bumped by `concurrency` when an op crashes with
    :info, cf. reference watch.clj:281-282).

    A plain __slots__ class, not a dataclass: the interpreter builds and
    restricts contexts several times per event (HOT LOOP #1), and frozen
    dataclass construction pays object.__setattr__ per field.  Restricted
    sub-contexts are memoized per instance — combinator walks restrict the
    same thread sets repeatedly at one instant.
    """

    __slots__ = ("time", "free", "workers", "rng", "concurrency",
                 "_sub", "_sorted_free")

    def __init__(self, time: int, free: frozenset, workers: dict,
                 rng: Any, concurrency: int):
        self.time = time
        self.free = free
        self.workers = workers
        self.rng = rng
        self.concurrency = concurrency
        self._sub: Optional[dict] = None
        self._sorted_free: Optional[list] = None

    def set_time(self, t: int) -> None:
        """Advance this context — and its memoized sub-contexts, which share
        the same clock — to virtual time t.  Lets the interpreter reuse one
        Context (and its restrict() memo) across polls while workers/free
        are unchanged and only time moves."""
        if self.time == t:
            return
        self.time = t
        if self._sub:
            for c in self._sub.values():
                c.set_time(t)

    def restrict(self, threads: frozenset) -> "Context":
        memo = self._sub
        if memo is None:
            memo = self._sub = {}
        else:
            got = memo.get(threads)
            if got is not None:
                return got
        w = self.workers
        cache = getattr(w, "sub_cache", None)
        sub = cache.get(threads) if cache is not None else None
        if sub is None:
            sub = _WorkersMap((t, p) for t, p in w.items() if t in threads)
            if cache is not None:
                cache[threads] = sub
        out = Context(time=self.time, free=self.free & threads,
                      workers=sub, rng=self.rng,
                      concurrency=self.concurrency)
        memo[threads] = out
        return out

    @property
    def client_threads(self) -> list:
        return sorted(t for t in self.workers if isinstance(t, int))

    @property
    def all_threads(self) -> frozenset:
        return frozenset(self.workers)

    @property
    def all_free(self) -> bool:
        return self.free == frozenset(self.workers)

    def some_free_process(self) -> Optional[Any]:
        """Pick a free process deterministically (seeded rng)."""
        cands = self._sorted_free
        if cands is None:
            cands = self._sorted_free = sorted(self.free, key=str)
        if not cands:
            return None
        t = self.rng.choice(cands)
        return self.workers[t]

    def thread_of(self, process: Any) -> Any:
        if not isinstance(process, int):
            return process  # "nemesis" etc.
        return process % self.concurrency


class Generator:
    """Base class; subclasses override op()/update()."""

    def op(self, test: Any, ctx: Context):
        raise NotImplementedError

    def update(self, test: Any, ctx: Context, event: Op) -> "Generator":
        return self


def ensure_gen(x: Any) -> Optional[Generator]:
    if x is None or isinstance(x, Generator):
        return x
    if isinstance(x, dict):
        return OnceOp(dict(x))
    if callable(x):
        return FnGen(x)
    if isinstance(x, (list, tuple)):
        return Seq(list(x), 0, None)
    if isinstance(x, Iterable):
        return Seq([], 0, iter(x))
    raise TypeError(f"cannot lift {x!r} to a generator")


def _fill_in(op_dict: dict, ctx: Context) -> Optional[Op]:
    """Assign process and earliest time to a raw op; None if no free thread."""
    op = Op(op_dict)
    if op.get("process") is None:
        p = ctx.some_free_process()
        if p is None:
            return None
        op["process"] = p
    if op.get("time") is None:
        op["time"] = ctx.time
    op.setdefault("type", "invoke")
    return op


# ---------------------------------------------------------------------------
# Leaves


@dataclass(frozen=True)
class OnceOp(Generator):
    """A plain map: emits exactly once."""

    proto: dict

    def op(self, test, ctx):
        op = _fill_in(self.proto, ctx)
        if op is None:
            return (PENDING, None, self)
        return (op, None_gen)


@dataclass(frozen=True)
class FnGen(Generator):
    """A function of (test, ctx) (or zero args): fresh op per emission.

    Mirrors jepsen fn-generators like register.clj:98-100 (`r`/`w`/`cas`).
    Exhausted when the function returns None.
    """

    f: Callable

    def _call(self, test, ctx):
        nparams = _arity_cache.get(self.f)
        if nparams is None:
            try:
                nparams = len(inspect.signature(self.f).parameters)
            except (TypeError, ValueError):
                nparams = 2
            _arity_cache[self.f] = nparams
        if nparams == 0:
            return self.f()
        if nparams == 1:
            return self.f(ctx)
        return self.f(test, ctx)

    def op(self, test, ctx):
        if not ctx.free:
            # Don't invoke f speculatively: a stateful source (e.g. popping
            # a finite list) would lose the produced op.
            return (PENDING, None, self)
        raw = self._call(test, ctx)
        if raw is None:
            return None
        op = _fill_in(raw, ctx)  # _fill_in copies via Op(raw)
        if op is None:
            return (PENDING, None, self)
        return (op, self)


@dataclass(frozen=True)
class Seq(Generator):
    """A sequence of sub-generators run in order; supports lazy iterables."""

    items: list  # materialized prefix (shared, append-only)
    idx: int
    it: Optional[Any]  # iterator for the lazy tail (shared)
    current: Optional[Generator] = None

    def _head(self):
        """Current sub-generator, materializing from the iterator on demand."""
        if self.current is not None:
            return self.current
        while self.idx >= len(self.items) and self.it is not None:
            try:
                self.items.append(next(self.it))
            except StopIteration:
                object.__setattr__(self, "it", None)
                break
        if self.idx < len(self.items):
            return ensure_gen(self.items[self.idx])
        return None

    def op(self, test, ctx):
        me = self
        while True:
            head = me._head()
            if head is None:
                return None
            res = head.op(test, ctx)
            if res is None:
                me = Seq(me.items, me.idx + 1, me.it, None)
                continue
            if res[0] == PENDING:
                _, wake, head2 = res
                if head2 is me.current:
                    return (PENDING, wake, me)
                return (PENDING, wake, Seq(me.items, me.idx, me.it, head2))
            op, head2 = res
            if head2 is me.current:
                return (op, me)
            return (op, Seq(me.items, me.idx, me.it, head2))

    def update(self, test, ctx, event):
        head = self._head()
        if head is None:
            return self
        h2 = head.update(test, ctx, event)
        if h2 is self.current:
            return self
        return Seq(self.items, self.idx, self.it, h2)


class _NoneGen(Generator):
    def op(self, test, ctx):
        return None


None_gen = _NoneGen()


# ---------------------------------------------------------------------------
# Combinators


@dataclass(frozen=True)
class Mix(Generator):
    """Random choice among sub-generators per emission (gen/mix)."""

    gens: tuple

    def op(self, test, ctx):
        alive = [(i, g) for i, g in enumerate(self.gens) if g is not None]
        if not alive:
            return None
        order = list(alive)
        ctx.rng.shuffle(order)
        pend_wake = "none"
        new = list(self.gens)
        changed = False
        for i, g in order:
            res = g.op(test, ctx)
            if res is None:
                new[i] = None
                changed = True
                continue
            if res[0] == PENDING:
                _, wake, g2 = res
                if g2 is not g:
                    new[i] = g2
                    changed = True
                pend_wake = _min_wake(pend_wake, wake)
                continue
            op, g2 = res
            if g2 is not g:
                new[i] = g2
                changed = True
            return (op, Mix(tuple(new)) if changed else self)
        if all(g is None for g in new):
            return None
        return (PENDING, None if pend_wake == "none" else pend_wake,
                Mix(tuple(new)) if changed else self)

    def update(self, test, ctx, event):
        new = tuple(g.update(test, ctx, event) if g else None
                    for g in self.gens)
        if all(a is b for a, b in zip(new, self.gens)):
            return self
        return Mix(new)


def _min_wake(a, b):
    if a == "none" or a is None:
        return b
    if b is None:
        return a
    return min(a, b)


@dataclass(frozen=True)
class Limit(Generator):
    """At most n ops (gen/limit), e.g. ops-per-key (register.clj:118)."""

    n: int
    gen: Optional[Generator]

    def op(self, test, ctx):
        if self.n <= 0 or self.gen is None:
            return None
        res = self.gen.op(test, ctx)
        if res is None:
            return None
        if res[0] == PENDING:
            _, wake, g2 = res
            if g2 is self.gen:
                return (PENDING, wake, self)
            return (PENDING, wake, Limit(self.n, g2))
        op, g2 = res
        return (op, Limit(self.n - 1, g2))

    def update(self, test, ctx, event):
        if self.gen is None:
            return self
        g2 = self.gen.update(test, ctx, event)
        return self if g2 is self.gen else Limit(self.n, g2)


@dataclass(frozen=True)
class Stagger(Generator):
    """Space ops ~uniform[0, 2*dt] apart overall (gen/stagger).

    dt is the *mean* gap; aggregate rate across all threads is ~1/dt, the
    semantics the reference relies on for `--rate` (etcd.clj:145,190-193).
    """

    dt: int
    gen: Optional[Generator]
    next_time: Optional[int] = None

    def op(self, test, ctx):
        if self.gen is None:
            return None
        res = self.gen.op(test, ctx)
        if res is None:
            return None
        if res[0] == PENDING:
            _, wake, g2 = res
            if g2 is self.gen:
                return (PENDING, wake, self)
            return (PENDING, wake, Stagger(self.dt, g2, self.next_time))
        op, g2 = res
        nt = self.next_time if self.next_time is not None else ctx.time
        t_emit = max(op["time"], nt)
        op["time"] = t_emit
        gap = int(ctx.rng.random() * 2 * self.dt)
        return (op, Stagger(self.dt, g2, t_emit + gap))

    def update(self, test, ctx, event):
        if self.gen is None:
            return self
        g2 = self.gen.update(test, ctx, event)
        return self if g2 is self.gen else Stagger(self.dt, g2,
                                                   self.next_time)


@dataclass(frozen=True)
class Delay(Generator):
    """Fixed dt between ops (gen/delay)."""

    dt: int
    gen: Optional[Generator]
    next_time: Optional[int] = None

    def op(self, test, ctx):
        if self.gen is None:
            return None
        res = self.gen.op(test, ctx)
        if res is None:
            return None
        if res[0] == PENDING:
            _, wake, g2 = res
            if g2 is self.gen:
                return (PENDING, wake, self)
            return (PENDING, wake, Delay(self.dt, g2, self.next_time))
        op, g2 = res
        nt = self.next_time if self.next_time is not None else ctx.time
        t_emit = max(op["time"], nt)
        op["time"] = t_emit
        return (op, Delay(self.dt, g2, t_emit + self.dt))

    def update(self, test, ctx, event):
        if self.gen is None:
            return self
        g2 = self.gen.update(test, ctx, event)
        return self if g2 is self.gen else Delay(self.dt, g2,
                                                 self.next_time)


@dataclass(frozen=True)
class Sleep(Generator):
    """Emit nothing for dt, then exhaust (gen/sleep)."""

    dt: int
    deadline: Optional[int] = None

    def op(self, test, ctx):
        dl = self.deadline if self.deadline is not None else ctx.time + self.dt
        if ctx.time >= dl:
            return None
        if dl == self.deadline:
            return (PENDING, dl, self)
        return (PENDING, dl, Sleep(self.dt, dl))


@dataclass(frozen=True)
class TimeLimit(Generator):
    """Stop emitting t after the first poll (gen/time-limit)."""

    t: int
    gen: Optional[Generator]
    deadline: Optional[int] = None

    def op(self, test, ctx):
        dl = self.deadline if self.deadline is not None else ctx.time + self.t
        if ctx.time >= dl or self.gen is None:
            return None
        res = self.gen.op(test, ctx)
        if res is None:
            return None
        if res[0] == PENDING:
            _, wake, g2 = res
            if g2 is self.gen and dl == self.deadline:
                return (PENDING, _min_wake(wake, dl), self)
            return (PENDING, _min_wake(wake, dl), TimeLimit(self.t, g2, dl))
        op, g2 = res
        if op["time"] >= dl:
            # Op would fire past the deadline: the limit cuts it off.
            return None
        if g2 is self.gen and dl == self.deadline:
            return (op, self)
        return (op, TimeLimit(self.t, g2, dl))

    def update(self, test, ctx, event):
        if self.gen is None:
            return self
        g2 = self.gen.update(test, ctx, event)
        return self if g2 is self.gen else TimeLimit(self.t, g2,
                                                     self.deadline)


@dataclass(frozen=True)
class Synchronize(Generator):
    """Wait until all workers are free before starting child (gen/synchronize)."""

    gen: Optional[Generator]
    started: bool = False

    def op(self, test, ctx):
        if self.gen is None:
            return None
        if not self.started and not ctx.all_free:
            return (PENDING, None, self)
        res = self.gen.op(test, ctx)
        if res is None:
            return None
        if res[0] == PENDING:
            _, wake, g2 = res
            if g2 is self.gen and self.started:
                return (PENDING, wake, self)
            return (PENDING, wake, Synchronize(g2, True))
        op, g2 = res
        if g2 is self.gen and self.started:
            return (op, self)
        return (op, Synchronize(g2, True))

    def update(self, test, ctx, event):
        if self.gen is None:
            return self
        g2 = self.gen.update(test, ctx, event)
        return self if g2 is self.gen else Synchronize(g2, self.started)


@dataclass(frozen=True)
class Log(Generator):
    """Emit one no-thread log pseudo-op (gen/log); interpreter prints it."""

    msg: str

    def op(self, test, ctx):
        op = Op(type="log", f="log", value=self.msg, process="__log__",
                time=ctx.time)
        return (op, None_gen)


@dataclass(frozen=True)
class OnThreads(Generator):
    """Restrict a generator to a thread subset (gen/on-threads and friends)."""

    threads: frozenset
    gen: Optional[Generator]

    def op(self, test, ctx):
        if self.gen is None:
            return None
        res = self.gen.op(test, ctx.restrict(self.threads))
        if res is None:
            return None
        if res[0] == PENDING:
            _, wake, g2 = res
            if g2 is self.gen:
                return (PENDING, wake, self)
            return (PENDING, wake, OnThreads(self.threads, g2))
        op, g2 = res
        if g2 is self.gen:
            return (op, self)
        return (op, OnThreads(self.threads, g2))

    def update(self, test, ctx, event):
        if self.gen is None:
            return self
        t = ctx.thread_of(event.get("process"))
        if t in self.threads:
            g2 = self.gen.update(test, ctx.restrict(self.threads), event)
            return self if g2 is self.gen else OnThreads(self.threads, g2)
        return self


@dataclass(frozen=True)
class Alt(Generator):
    """Poll several generators; emit the op with the soonest time.

    The combination engine behind reserve and nemesis/clients routing.
    Branches whose thread sets are disjoint run concurrently.
    """

    branches: tuple  # of OnThreads

    def op(self, test, ctx):
        best = None  # (op, idx, gen2)
        pend_wake = "none"
        any_alive = False
        new = list(self.branches)
        changed = False
        for i, b in enumerate(self.branches):
            res = b.op(test, ctx)
            if res is None:
                continue
            any_alive = True
            if res[0] == PENDING:
                _, wake, b2 = res
                if b2 is not b:
                    new[i] = b2
                    changed = True
                pend_wake = _min_wake(pend_wake, wake)
                continue
            op, b2 = res
            if best is None or op["time"] < best[0]["time"]:
                best = (op, i, b2)
        if best is not None:
            op, i, b2 = best
            if b2 is not new[i]:
                new[i] = b2
                changed = True
            return (op, Alt(tuple(new)) if changed else self)
        if not any_alive:
            return None
        return (PENDING, None if pend_wake == "none" else pend_wake,
                Alt(tuple(new)) if changed else self)

    def update(self, test, ctx, event):
        new = tuple(b.update(test, ctx, event) for b in self.branches)
        if all(a is b for a, b in zip(new, self.branches)):
            return self
        return Alt(new)


@dataclass(frozen=True)
class EachThread(Generator):
    """An independent copy of the generator per thread (gen/each-thread).

    Used for the watch workload's :final-watch (watch.clj:376-379).
    """

    spec: Any
    children: Any = None  # tuple of (thread, gen) once initialized
    done: frozenset = frozenset()

    def _init(self, ctx):
        if self.children is not None:
            return self
        ch = tuple((t, ensure_gen(self.spec)) for t in sorted(
            ctx.workers, key=str))
        return replace(self, children=ch)

    def op(self, test, ctx):
        me = self._init(ctx)
        best = None
        pend_wake = "none"
        alive = False
        new = list(me.children)
        changed = False
        for i, (t, g) in enumerate(me.children):
            if g is None:
                continue
            alive = True
            if t not in ctx.free:
                continue
            res = g.op(test, ctx.restrict(frozenset([t])))
            if res is None:
                new[i] = (t, None)
                changed = True
                continue
            if res[0] == PENDING:
                _, wake, g2 = res
                if g2 is not g:
                    new[i] = (t, g2)
                    changed = True
                pend_wake = _min_wake(pend_wake, wake)
                continue
            op, g2 = res
            if best is None or op["time"] < best[0]["time"]:
                best = (op, i, g2)
        if best is not None:
            op, i, g2 = best
            t = new[i][0]
            if g2 is not new[i][1]:
                new[i] = (t, g2)
                changed = True
            return (op, EachThread(me.spec, tuple(new), me.done)
                    if changed else me)
        if not any(g is not None for _, g in new):
            return None
        if not alive:
            return None
        return (PENDING, None if pend_wake == "none" else pend_wake,
                EachThread(me.spec, tuple(new), me.done) if changed else me)

    def update(self, test, ctx, event):
        if self.children is None:
            return self
        t_ev = ctx.thread_of(event.get("process"))
        new = tuple(
            (t, g.update(test, ctx.restrict(frozenset([t])), event)
             if (g is not None and t == t_ev) else g)
            for t, g in self.children)
        if all(a[1] is b[1] for a, b in zip(new, self.children)):
            return self
        return replace(self, children=new)


@dataclass(frozen=True)
class FMap(Generator):
    """Apply f to each emitted op (gen/map); used to wrap values."""

    f: Callable
    gen: Optional[Generator]

    def op(self, test, ctx):
        if self.gen is None:
            return None
        res = self.gen.op(test, ctx)
        if res is None:
            return None
        if res[0] == PENDING:
            _, wake, g2 = res
            return (PENDING, wake,
                    self if g2 is self.gen else FMap(self.f, g2))
        op, g2 = res
        return (self.f(op), self if g2 is self.gen else FMap(self.f, g2))

    def update(self, test, ctx, event):
        if self.gen is None:
            return self
        g2 = self.gen.update(test, ctx, event)
        return self if g2 is self.gen else FMap(self.f, g2)


@dataclass(frozen=True)
class Cycle(Generator):
    """Restart the generator spec each time it exhausts (gen/cycle)."""

    spec: Any
    current: Optional[Generator] = None
    times: Optional[int] = None

    def op(self, test, ctx):
        me = self
        for _ in range(2):
            cur = me.current if me.current is not None else ensure_gen(me.spec)
            res = cur.op(test, ctx)
            if res is None:
                if me.times is not None and me.times <= 1:
                    return None
                nt = None if me.times is None else me.times - 1
                me = Cycle(me.spec, None, nt)
                continue
            if res[0] == PENDING:
                _, wake, g2 = res
                if g2 is me.current:
                    return (PENDING, wake, me)
                return (PENDING, wake, Cycle(me.spec, g2, me.times))
            op, g2 = res
            if g2 is me.current:
                return (op, me)
            return (op, Cycle(me.spec, g2, me.times))
        return (PENDING, None, me)

    def update(self, test, ctx, event):
        if self.current is None:
            return self
        g2 = self.current.update(test, ctx, event)
        return self if g2 is self.current else Cycle(self.spec, g2, self.times)


# ---------------------------------------------------------------------------
# Public constructors (jepsen.generator surface)


def once(x) -> Generator:
    return ensure_gen(dict(x) if isinstance(x, dict) else x)


def repeat(x, times: Optional[int] = None) -> Generator:
    return Cycle(x, None, times)


def cycle(x, times: Optional[int] = None) -> Generator:
    return Cycle(x, None, times)


def seq(*gens) -> Generator:
    return Seq(list(gens), 0, None)


def fn_gen(f) -> Generator:
    return FnGen(f)


def mix(gens: list) -> Generator:
    return Mix(tuple(ensure_gen(g) for g in gens))


def limit(n: int, gen) -> Generator:
    return Limit(n, ensure_gen(gen))


def stagger(dt: float, gen) -> Generator:
    return Stagger(int(dt), ensure_gen(gen))


def delay(dt: float, gen) -> Generator:
    return Delay(int(dt), ensure_gen(gen))


def sleep_gen(dt: float) -> Generator:
    return Sleep(int(dt))


def time_limit(t: float, gen) -> Generator:
    return TimeLimit(int(t), ensure_gen(gen))


def synchronize(gen) -> Generator:
    return Synchronize(ensure_gen(gen))


def phases(*gens) -> Generator:
    """Sequential phases, each starting only when all workers are free."""
    return Seq([Synchronize(ensure_gen(g)) for g in gens], 0, None)


def log(msg: str) -> Generator:
    return Log(msg)


def on_threads(threads, gen) -> Generator:
    return OnThreads(frozenset(threads), ensure_gen(gen))


def any_gen(*gens) -> Generator:
    return Alt(tuple(ensure_gen(g) for g in gens))


@dataclass(frozen=True)
class _ClientsOnly(Generator):
    """OnThreads over all integer threads, resolved lazily from ctx."""

    gen: Optional[Generator]

    def _restricted(self, ctx):
        return ctx.restrict(frozenset(t for t in ctx.workers
                                      if isinstance(t, int)))

    def op(self, test, ctx):
        if self.gen is None:
            return None
        res = self.gen.op(test, self._restricted(ctx))
        if res is None:
            return None
        if res[0] == PENDING:
            _, wake, g2 = res
            return (PENDING, wake,
                    self if g2 is self.gen else _ClientsOnly(g2))
        op, g2 = res
        return (op, self if g2 is self.gen else _ClientsOnly(g2))

    def update(self, test, ctx, event):
        if self.gen is None or not isinstance(event.get("process"), int):
            return self
        g2 = self.gen.update(test, self._restricted(ctx), event)
        return self if g2 is self.gen else _ClientsOnly(g2)


def clients(client_gen, nemesis_gen=None) -> Generator:
    """Route client_gen to client threads (gen/clients)."""
    branches = [_ClientsOnly(ensure_gen(client_gen))]
    if nemesis_gen is not None:
        branches.append(OnThreads(frozenset([NEMESIS]),
                                  ensure_gen(nemesis_gen)))
    return branches[0] if len(branches) == 1 else Alt(tuple(branches))


def nemesis(nemesis_gen, client_gen=None) -> Generator:
    """Route nemesis_gen to the nemesis thread; client_gen (if given) to
    clients — the 2-arity threading shape at etcd.clj:146-149."""
    branches = [OnThreads(frozenset([NEMESIS]), ensure_gen(nemesis_gen))]
    if client_gen is not None:
        branches.append(_ClientsOnly(ensure_gen(client_gen)))
    return branches[0] if len(branches) == 1 else Alt(tuple(branches))


@dataclass(frozen=True)
class Reserve(Generator):
    """Partition client threads into ranges, one generator per range
    (gen/reserve): reserve(n1, g1, n2, g2, ..., default).

    The first n1 client threads run g1, the next n2 run g2, ...; remaining
    threads run the default.  cf. register.clj:118, set.clj:47,
    watch.clj:374-377.
    """

    counts: tuple
    gens: tuple  # len(counts)+1, last is the default (may be None)
    resolved: Any = None  # tuple of OnThreads branches once ctx seen

    def _resolve(self, ctx):
        if self.resolved is not None:
            return self
        threads = sorted(t for t in ctx.workers if isinstance(t, int))
        if sum(self.counts) > len(threads):
            raise ValueError(
                f"reserve: {sum(self.counts)} reserved threads > "
                f"{len(threads)} client threads")
        branches = []
        at = 0
        for n, g in zip(self.counts, self.gens):
            branches.append(OnThreads(frozenset(threads[at:at + n]),
                                      ensure_gen(g)))
            at += n
        default = self.gens[len(self.counts)]
        if threads[at:]:  # an empty branch would pend forever
            branches.append(OnThreads(frozenset(threads[at:]),
                                      ensure_gen(default)))
        return replace(self, resolved=Alt(tuple(branches)))

    def op(self, test, ctx):
        me = self._resolve(ctx)
        res = me.resolved.op(test, ctx)
        if res is None:
            return None
        if res[0] == PENDING:
            _, wake, alt2 = res
            if alt2 is me.resolved:
                return (PENDING, wake, me)
            return (PENDING, wake, Reserve(me.counts, me.gens, alt2))
        op, alt2 = res
        if alt2 is me.resolved:
            return (op, me)
        return (op, Reserve(me.counts, me.gens, alt2))

    def update(self, test, ctx, event):
        me = self._resolve(ctx)
        alt2 = me.resolved.update(test, ctx, event)
        return me if alt2 is me.resolved else Reserve(me.counts, me.gens,
                                                      alt2)


def reserve(*args) -> Generator:
    """reserve(n1, g1, n2, g2, ..., default_gen)."""
    if len(args) % 2 != 1:
        raise ValueError("reserve takes pairs of (count, gen) plus a default")
    counts = tuple(args[0:-1:2])
    gens = tuple(list(args[1:-1:2]) + [args[-1]])
    return Reserve(counts, gens)


def each_thread(spec) -> Generator:
    return EachThread(spec)


def f_map(f, gen) -> Generator:
    return FMap(f, ensure_gen(gen))
