from .core import (
    Context, ensure_gen, Generator, PENDING,
    once, repeat, seq, fn_gen, mix, limit, stagger, delay, sleep_gen,
    time_limit, phases, log, reserve, clients, nemesis, on_threads,
    each_thread, any_gen, cycle, synchronize, f_map,
)
from . import independent

__all__ = [
    "Context", "ensure_gen", "Generator", "PENDING",
    "once", "repeat", "seq", "fn_gen", "mix", "limit", "stagger", "delay",
    "sleep_gen", "time_limit", "phases", "log", "reserve", "clients",
    "nemesis", "on_threads", "each_thread", "any_gen", "cycle",
    "synchronize", "f_map", "independent",
]
