"""Key-level data parallelism: jepsen.independent re-designed.

``concurrent_generator(n, keys, gen_fn)`` partitions client threads into
groups of ``n``; each group works through keys from a shared (possibly
infinite) key sequence, running ``gen_fn(key)`` with op values wrapped as
``(key, v)`` tuples.  This is the main data-parallel axis of the framework:
the matching ``independent`` *checker* (checkers/independent.py) splits the
history back per key — and on TPU, vmaps the per-key linearizability search
over the key batch.

Reference: register workload composition at ``register.clj:113-119``
(``independent/concurrent-generator (* 2 n) (range) (fn [k] ...)``).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Callable, Iterable, Optional

from ..core.op import Op
from .core import (
    Generator, Context, PENDING, ensure_gen, _min_wake,
)

EXHAUSTED = object()


class KeySeq:
    """Append-only memo over a (possibly infinite) key iterable: shared,
    deterministic, safe for the committed-poll protocol."""

    def __init__(self, keys: Iterable):
        self._memo: list = []
        self._it = iter(keys)

    def get(self, i: int):
        while i >= len(self._memo) and self._it is not None:
            try:
                self._memo.append(next(self._it))
            except StopIteration:
                self._it = None
        return self._memo[i] if i < len(self._memo) else EXHAUSTED


def tuple_value(k: Any, v: Any) -> tuple:
    return (k, v)


def untuple(op: Op) -> Op:
    """Strip the (key, v) wrapper from an op's value."""
    v = op.get("value")
    if isinstance(v, tuple) and len(v) == 2:
        return op.evolve(value=v[1])
    return op


@dataclass(frozen=True)
class ConcurrentGenerator(Generator):
    """Thread groups of size n, each processing keys independently."""

    n: int
    keys: KeySeq
    gen_fn: Callable
    # (threads_frozenset, key_or_None, gen_or_None) per group, once resolved
    groups: Optional[tuple] = None
    next_key: int = 0

    def _resolve(self, ctx: Context) -> "ConcurrentGenerator":
        if self.groups is not None:
            return self
        threads = sorted(t for t in ctx.workers if isinstance(t, int))
        gs = []
        for at in range(0, len(threads) - self.n + 1, self.n):
            gs.append((frozenset(threads[at:at + self.n]), None, None))
        if not gs:
            raise ValueError(
                f"concurrent_generator: {len(threads)} client threads is "
                f"fewer than group size {self.n}")
        return replace(self, groups=tuple(gs))

    def op(self, test, ctx):
        me = self._resolve(ctx)
        # One mutable copy of the group table per poll; a new generator
        # instance is built at most once, and only when something moved.
        gs = list(me.groups)
        next_key = me.next_key
        changed = False
        best = None  # (op, i, key, gen2, threads)
        pend_wake = "none"
        for i in range(len(gs)):
            threads, key, g = gs[i]
            if g is None:
                k = me.keys.get(next_key)
                if k is EXHAUSTED:
                    continue  # keys exhausted; group retires
                next_key += 1
                key, g = k, ensure_gen(me.gen_fn(k))
                gs[i] = (threads, key, g)
                changed = True
            sub = ctx.restrict(threads)
            # A group may need several polls if its gen exhausts: move to
            # the next key immediately.
            while True:
                res = g.op(test, sub)
                if res is None:
                    k = me.keys.get(next_key)
                    if k is EXHAUSTED:
                        g = None
                        break
                    next_key += 1
                    key, g = k, ensure_gen(me.gen_fn(k))
                    gs[i] = (threads, key, g)
                    changed = True
                    continue
                break
            if g is None:
                gs[i] = (threads, None, None)
                changed = True
                continue
            if res[0] == PENDING:
                _, wake, g2 = res
                pend_wake = _min_wake(pend_wake, wake)
                if g2 is not g:
                    gs[i] = (threads, key, g2)
                    changed = True
                continue
            op, g2 = res
            if best is None or op["time"] < best[0]["time"]:
                best = (op, i, key, g2, threads)
        if best is not None:
            op, i, key, g2, threads = best
            if g2 is not gs[i][2]:
                gs[i] = (threads, key, g2)
                changed = True
            if changed:
                me = ConcurrentGenerator(me.n, me.keys, me.gen_fn,
                                         tuple(gs), next_key)
            wrapped = op.evolve(value=(key, op.get("value")))
            return (wrapped, me)
        alive = any(g is not None for _, _, g in gs) \
            or me.keys.get(next_key) is not EXHAUSTED
        if not alive:
            return None
        if changed:
            me = ConcurrentGenerator(me.n, me.keys, me.gen_fn,
                                     tuple(gs), next_key)
        return (PENDING, None if pend_wake == "none" else pend_wake, me)

    def update(self, test, ctx, event):
        if self.groups is None:
            return self
        p = event.get("process")
        if not isinstance(p, int):
            return self
        t = ctx.thread_of(p)
        for i, (threads, key, g) in enumerate(self.groups):
            if g is not None and t in threads:
                g2 = g.update(test, ctx.restrict(threads), untuple(event))
                if g2 is g:
                    return self
                gs = list(self.groups)
                gs[i] = (threads, key, g2)
                return ConcurrentGenerator(self.n, self.keys, self.gen_fn,
                                           tuple(gs), self.next_key)
        return self


def concurrent_generator(n: int, keys: Iterable, gen_fn: Callable) -> Generator:
    return ConcurrentGenerator(n, KeySeq(keys), gen_fn)


def history_keys(history) -> list:
    """All keys appearing in (key, v) tuple values, in first-seen order."""
    seen: dict = {}
    for op in history:
        v = op.get("value")
        if isinstance(v, tuple) and len(v) == 2:
            seen.setdefault(v[0], None)
    return list(seen)


def subhistory(history, key) -> list:
    """Ops for one key, values unwrapped; preserves op indices."""
    out = []
    for op in history:
        v = op.get("value")
        if isinstance(v, tuple) and len(v) == 2 and v[0] == key:
            out.append(op.evolve(value=v[1]))
    return out


def subhistories(history) -> dict:
    """All per-key subhistories in ONE pass over the parent history:
    ``{key: ops}`` with keys in first-seen order, values unwrapped and
    op indices preserved — equivalent to calling ``subhistory`` per key
    of ``history_keys`` but O(N) instead of O(K * N), which is what the
    batched checker axis needs (512 keys would otherwise re-scan the
    full history 512 times before any checking starts)."""
    out: dict = {}
    for op in history:
        v = op.get("value")
        if isinstance(v, tuple) and len(v) == 2:
            ops = out.get(v[0])
            if ops is None:
                ops = out[v[0]] = []
            ops.append(op.evolve(value=v[1]))
    return out
