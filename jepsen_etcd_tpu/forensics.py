"""History forensics: REPL helpers over saved stores.

Re-designs the reference's list-append investigation toolkit
(``etcd.clj:259-346``), written to debug an etcdctl client leaking state
between test runs: given debug-mode histories (whose written values
carry provenance, workloads/debug.py), these extract which *runs* the
values read back came from (``txn_dirs`` — a value from a different
run's dir is the smoking gun), and find duplicate mod-revisions for the
same (key, value) (``duplicate_revisions``).

Works over live History objects or saved stores (``load_history``).
"""

from __future__ import annotations

import json
import os
from collections import defaultdict
from typing import Any, Iterable, Optional

from .core.history import History


def load_history(run_dir: str) -> History:
    """Read a saved run's history.jsonl."""
    with open(os.path.join(run_dir, "history.jsonl")) as f:
        return History.from_jsonl(f.read())


def all_runs(store_base: str = "store") -> list[str]:
    """All saved run dirs under a store base, oldest first
    (store/all-tests analog, etcd.clj:283)."""
    out = []
    if not os.path.isdir(store_base):
        return out
    for test_name in sorted(os.listdir(store_base)):
        tdir = os.path.join(store_base, test_name)
        if not os.path.isdir(tdir) or test_name == "latest":
            continue
        for run in sorted(os.listdir(tdir)):
            rdir = os.path.join(tdir, run)
            if run != "latest" and os.path.isdir(rdir) and \
                    os.path.exists(os.path.join(rdir, "history.jsonl")):
                out.append(rdir)
    return out


def _debug_values(res: Any) -> Iterable[dict]:
    """Yield provenance-wrapped values out of a raw txn result."""
    if not isinstance(res, dict):
        return
    for entry in res.get("results", ()):
        if not (isinstance(entry, (list, tuple)) and len(entry) == 2):
            continue
        _, payload = entry
        if isinstance(payload, dict):
            v = payload.get("value")
            if isinstance(v, dict) and "dir" in v:
                yield v


def txn_dirs(history) -> set:
    """Set of store-dir names seen in any txn's read results
    (txn-dirs, etcd.clj:265-276): values read from a *different* run's
    dir prove state leaked across runs."""
    dirs = set()
    for op in history:
        dbg = op.get("debug")
        if not isinstance(dbg, dict):
            continue
        for res_key in ("read-res", "txn-res"):
            res = dbg.get(res_key)
            if res_key == "read-res" and isinstance(res, dict):
                # append's phase-1 shape: {"reads": {k: kv}, ...}
                for kv in (res.get("reads") or {}).values():
                    if isinstance(kv, dict) and isinstance(
                            kv.get("value"), dict) and \
                            "dir" in kv["value"]:
                        dirs.add(kv["value"]["dir"])
            else:
                for v in _debug_values(res):
                    dirs.add(v["dir"])
    return dirs


def all_txn_dirs(store_base: str = "store") -> dict:
    """Map run dir -> txn_dirs(history) for every saved run with any
    (all-txns-dirs, etcd.clj:279-289)."""
    out = {}
    for rdir in all_runs(store_base):
        dirs = txn_dirs(load_history(rdir))
        if dirs:
            out[rdir] = dirs
    return out


def ops_involving(k, history) -> list:
    """Ops whose txn touches key k (ops-involving, etcd.clj:291-300)."""
    out = []
    for op in history:
        if op.get("f") != "txn":
            continue
        v = op.get("value")
        if isinstance(v, (list, tuple)) and any(
                isinstance(m, (list, tuple)) and len(m) >= 2 and m[1] == k
                for m in v):
            out.append(op)
    return out


def wr_op_revisions(op) -> list:
    """Revision maps from one debug-mode txn op
    (wr-op-revisions, etcd.clj:302-330):

        {"type": "w"|"r", "index": op index, "key": k,
         "value": v, "mod-revision": r}

    writes report their prev-kv (the state they overwrote); reads report
    the kv they observed."""
    dbg = op.get("debug")
    if not isinstance(dbg, dict):
        return []
    res = dbg.get("txn-res")
    if not isinstance(res, dict):
        return []
    out = []
    for entry in res.get("results", ()):
        if not (isinstance(entry, (list, tuple)) and len(entry) == 2):
            continue
        kind, payload = entry
        if payload is None or not isinstance(payload, dict):
            continue
        v = payload.get("value")
        if isinstance(v, dict) and "value" in v:
            v = v["value"]  # strip provenance wrapper
        out.append({
            "type": "w" if kind == "put" else "r",
            "index": op.get("index"),
            "key": payload.get("key"),
            "value": v,
            "mod-revision": payload.get("mod-revision"),
        })
    return out


def wr_ops_revisions(ops) -> list:
    """All revision maps from many ops (etcd.clj:332-335)."""
    out = []
    for op in ops:
        out.extend(wr_op_revisions(op))
    return out


def duplicate_revisions(ops) -> dict:
    """(key, value) -> revision maps, where the same (key, value) pair
    appears under more than one mod-revision (duplicate-revisions,
    etcd.clj:337-346) — on a healthy etcd each written value gets one
    revision, so duplicates expose cross-run leakage or lost updates."""
    by_kv: dict = defaultdict(list)
    for rm in wr_ops_revisions(ops):
        if rm["key"] is not None:
            by_kv[(rm["key"], json.dumps(rm["value"], default=repr,
                                         sort_keys=True))].append(rm)
    return {kv: rms for kv, rms in sorted(by_kv.items())
            if len({rm["mod-revision"] for rm in rms}) > 1}
