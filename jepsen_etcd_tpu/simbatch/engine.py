"""Lockstep batched generator: S seeds' client/nemesis simulations as
columnar numpy steps, histories born as OpColumns.

Where ``runner/sim.py`` interprets ONE seed's discrete-event simulation
on the CPython event loop (epoch-v1), this engine advances S seeds at
once: every step pops one due event per live seed from a
:class:`~..simbatch.heap.BatchHeap`, applies the register/set client
state machines as ``(S,)``-wide masked array ops, and appends one
``(S,)`` column per history field. At the end, each seed's rows gather
straight into a ``core/history.py`` OpColumns — no per-op dicts are
ever built, so histories enter the dict-free checker pipeline with
zero conversion.

Determinism contract (generator epoch-v2; see the epoch ledger in
runner/sim.py):

- Per-seed histories are a pure function of ``(seed, BatchConfig)`` —
  every random draw comes from that seed's own
  ``np.random.default_rng(seed)`` block, pre-drawn before the loop, and
  heap sequence numbers advance per seed. Batch composition (which
  other seeds ride along, and how many) cannot perturb a history; the
  16-seed golden-hash pin in tests/test_simbatch.py holds seed-by-seed.
- Event times carry a lane residue (``time = t_ns * STRIDE + lane``)
  so no two lanes of one seed ever share an instant; the epoch-v2
  same-instant rule (ascending lane, then push seq) therefore never
  has to arbitrate inside generated histories — it is pinned at the
  heap level by unit tests instead.
- The linearization point of every client op is its completion
  instant, and completions are totally ordered per seed, so every
  generated history is linearizable by construction. That is what
  makes the epoch-v2 vs epoch-v1 fuzz a *verdict*-equality check:
  histories differ op-by-op across epochs (the point of declaring an
  epoch), but any state-machine bug here flips a checker verdict.

Timeouts model indeterminacy: while a nemesis window is open, each
completion may instead resolve as an ``info`` op (the invoke's payload,
``{"error": "timeout"}``), the register/set state is NOT advanced, and
the process retires exactly like epoch-v1's client error path
(``proc += lanes``).

Performance shape: per-op draw planes (f, write/cas values, latency,
gap, timeout, payload-kind) are pre-folded into ONE ``(R, S, L, O)``
stack so each step gathers single ``(R, S)`` slabs instead of ~10
separate advanced-index reads. And because invoke rows carry no
machine state — every field is a pure draw — the heap only schedules
COMPLETION and nemesis events: each completion step emits the
completion row *and* the next op's invoke row with its proper (later)
timestamp, and the finish phase restores each seed's global row order
with one argsort over the (unique) times. Step count is therefore one
per completion, not one per history row.
"""

from __future__ import annotations

from bisect import insort

import numpy as np

from ..core.history import History, OpColumns
from .heap import EPOCH_V1, EPOCH_V2, BatchHeap

GEN_EPOCH_V1 = EPOCH_V1
GEN_EPOCH_V2 = EPOCH_V2

SUPPORTED_WORKLOADS = ("register", "set")

#: lane-residue stride: event times are ``t_ns * STRIDE + lane``, so
#: lane count (clients + 1 nemesis lane) must stay below it
STRIDE = 64

KIND_INVOKE = 0
KIND_COMPLETE = 1
KIND_NEM = 2

# history type codes (core/history.py _TYPE_CODES order)
TC_INVOKE, TC_OK, TC_FAIL, TC_INFO = 0, 1, 2, 3

# payload kinds: how a row's (va, vb, vc) int slots decode to a value
PK_REG_RD_INV = 1
PK_REG_RD_OK = 2
PK_REG_WR_INV = 3
PK_REG_WR_OK = 4
PK_REG_CAS_INV = 5
PK_REG_CAS_OK = 6
PK_REG_CAS_FAIL = 7
PK_SET_ADD = 8
PK_SET_RD_INV = 9
PK_SET_RD_OK = 10
PK_NEM = 11

# register f codes (f_table prefix) / set f codes
FC_READ, FC_WRITE, FC_CAS = 0, 1, 2
FC_ADD, FC_SRD = 0, 1

#: per-fault probability that a completion inside an open nemesis
#: window resolves as a timeout info instead
P_TIMEOUT = {"partition": 0.25, "latency": 0.06, "kill": 0.18}
P_TIMEOUT_DEFAULT = 0.12

#: stale-read injection rate (inject_stale_reads knob; the draw is
#: always made so the knob cannot shift any other draw). With a fault
#: schedule present the injection models a stale read served by a
#: partitioned replica: it fires only while a partition window is OPEN
#: (guided campaigns steer toward exactly those cells). With no faults
#: at all it stays unconditional — the PR 13 regression template.
STALE_P = 0.25

#: ns between a nemesis invoke and its :info (fault apply latency)
NEM_APPLY_NS = 2_000_000
#: fault/heal cycles per nemesis per run
NEM_CYCLES = 4

#: nemesis start-op values by fault kind (stop value is always None),
#: mirroring nemesis/faults.py specs
NEM_START_VALUE = {
    "partition": "majority",
    "latency": {"delta-ms": 40.0, "jitter-ms": 8.0},
}


def supports(workload: str) -> bool:
    return workload in SUPPORTED_WORKLOADS


def _norm_schedule(schedule, nemeses):
    """Normalize an explicit nemesis schedule to a sorted tuple of
    ``(start_ns, kind, hold_ns)`` windows. ``kind`` must name a fault
    in ``nemeses`` (the window replays that fault's start/stop pair)."""
    if schedule is None:
        return None
    out = []
    for w in schedule:
        start, kind, hold = w
        if kind not in nemeses:
            raise ValueError(f"schedule window kind {kind!r} not in "
                             f"nemeses {tuple(nemeses)!r}")
        out.append((int(start), str(kind), int(hold)))
    out.sort(key=lambda w: (w[0], w[2], w[1]))
    return tuple(out)


class BatchConfig:
    """Sizing + workload knobs; with a seed, fully determines one
    history. ``from_opts`` is the stable opts→config mapping the
    campaign router and bench use (changing it would re-key every
    pinned golden hash — bump the epoch instead).

    ``nem_schedule`` replays an explicit window list instead of the
    drawn nemesis cycles; draws are still made in full, so a config
    with no schedule is bit-identical to the pre-schedule epoch.
    ``partition_shape``/``latency_ms``/``drop_prob`` are the guided
    mutation knobs: shape swaps the partition start value, latency
    scales the latency-window timeout rate, drop_prob adds a flat
    timeout rate inside every open window."""

    __slots__ = ("workload", "nemeses", "lanes", "readers", "keys",
                 "ops_per_lane", "rate", "key_offset",
                 "inject_stale_reads", "nem_schedule",
                 "partition_shape", "latency_ms", "drop_prob")

    def __init__(self, workload="register", nemeses=(), lanes=8,
                 ops_per_lane=64, rate=200.0, keys=None, readers=None,
                 key_offset=0, inject_stale_reads=False,
                 nem_schedule=None, partition_shape=None,
                 latency_ms=None, drop_prob=0.0):
        if workload not in SUPPORTED_WORKLOADS:
            raise ValueError(f"simbatch does not support workload "
                             f"{workload!r} (supported: "
                             f"{SUPPORTED_WORKLOADS})")
        self.workload = workload
        self.nemeses = tuple(nemeses or ())
        self.lanes = max(2, min(int(lanes), STRIDE - 2))
        r = int(readers) if readers is not None else self.lanes // 2
        self.readers = max(1, min(self.lanes - 1, r))
        k = int(keys) if keys is not None else max(1, self.lanes // 4)
        self.keys = max(1, k)
        self.ops_per_lane = max(2, int(ops_per_lane))
        self.rate = float(rate) if rate else 200.0
        self.key_offset = int(key_offset)
        self.inject_stale_reads = bool(inject_stale_reads)
        self.nem_schedule = _norm_schedule(nem_schedule, self.nemeses)
        self.partition_shape = (str(partition_shape)
                                if partition_shape else None)
        self.latency_ms = (float(latency_ms)
                           if latency_ms is not None else None)
        self.drop_prob = min(1.0, max(0.0, float(drop_prob or 0.0)))

    @classmethod
    def from_opts(cls, opts: dict) -> "BatchConfig":
        nodes = opts.get("nodes") or ["n1", "n2", "n3"]
        conc = int(opts.get("concurrency") or 2 * len(nodes))
        lanes = max(2, min(conc, 16))
        rate = float(opts.get("rate") or 200.0)
        tl = float(opts.get("time_limit") or 30.0)
        total = max(2 * lanes, int(tl * rate))
        return cls(
            workload=opts.get("workload", "register"),
            nemeses=tuple(opts.get("nemesis") or ()),
            lanes=lanes,
            ops_per_lane=max(2, total // lanes),
            rate=rate,
            key_offset=int(opts.get("key_offset") or 0),
            inject_stale_reads=bool(opts.get("inject_stale_reads")),
            nem_schedule=opts.get("nem_schedule"),
            partition_shape=opts.get("nem_partition_shape"),
            latency_ms=opts.get("nem_latency_ms"),
            drop_prob=opts.get("nem_drop_prob") or 0.0,
        )

    def to_dict(self) -> dict:
        """JSON-safe round-trip: ``BatchConfig(**cfg.to_dict())`` —
        shrink artifacts persist this so replay does not depend on the
        opts→config mapping staying stable."""
        return {
            "workload": self.workload, "nemeses": list(self.nemeses),
            "lanes": self.lanes, "readers": self.readers,
            "keys": self.keys, "ops_per_lane": self.ops_per_lane,
            "rate": self.rate, "key_offset": self.key_offset,
            "inject_stale_reads": self.inject_stale_reads,
            "nem_schedule": ([list(w) for w in self.nem_schedule]
                             if self.nem_schedule is not None else None),
            "partition_shape": self.partition_shape,
            "latency_ms": self.latency_ms, "drop_prob": self.drop_prob,
        }

    def cache_key(self) -> tuple:
        """Hashable identity of everything that shapes a generated
        history (besides the seed) — the campaign router coalesces a
        cell only when this whole tuple matches, so guided mutants with
        distinct schedules/knobs never share a generate() call."""
        return (self.workload, self.nemeses, self.lanes, self.readers,
                self.keys, self.ops_per_lane, self.rate,
                self.key_offset, self.inject_stale_reads,
                self.nem_schedule, self.partition_shape,
                self.latency_ms, self.drop_prob)

    def f_table(self) -> list:
        base = (["read", "write", "cas"] if self.workload == "register"
                else ["add", "read"])
        for kind in self.nemeses:
            base.append(f"start-{kind}")
            base.append(f"stop-{kind}")
        return base

    def nem_f_base(self) -> int:
        return 3 if self.workload == "register" else 2


def schedule_span(config: BatchConfig) -> int:
    """Rough per-lane wall span of a run in ns — the same arithmetic
    ``_draws`` uses to space nemesis cycles. Guided mutations draw new
    window start/hold times inside this span."""
    gap_ns = max(1_000_000, int(config.lanes * 1e9 / config.rate))
    return config.ops_per_lane * (gap_ns + 3_000_000)


def default_schedule(config: BatchConfig, seed: int) -> list:
    """Materialize the DRAWN nemesis schedule of ``(config, seed)`` as
    an explicit ``[(start_ns, kind, hold_ns), ...]`` window list.

    Replaying it through ``nem_schedule`` reproduces the drawn run
    bit-for-bit (pinned by tests): the phase machine's absolute event
    times are start = prev stop-ok + wait, stop-ok = start +
    2*NEM_APPLY_NS + hold, so the wait/hold draws convert to absolute
    windows and back exactly. This is the shrinker's starting corpus
    for runs that never carried an explicit schedule."""
    if not config.nemeses:
        return []
    d = _draws(config, [int(seed)])
    out, tcur = [], 0
    for c in range(NEM_CYCLES):
        start = tcur + int(d["nwait"][0, c])
        hold = int(d["nhold"][0, c])
        out.append((start, config.nemeses[int(d["nkind"][0, c])], hold))
        tcur = start + 2 * NEM_APPLY_NS + hold
    return out


def _schedule_arrays(schedules, nemeses):
    """Convert per-seed explicit window lists into the phase machine's
    ``(nwait, nhold, nkind, n_cycles)`` arrays (ns, pre-STRIDE).

    Inverse of :func:`default_schedule`'s absolute-time conversion;
    short schedules are padded (padding is never reached because the
    machine stops pushing at each seed's own cycle count)."""
    S = len(schedules)
    C = max([len(sc) for sc in schedules] + [1])
    nwait = np.ones((S, C), np.int64)
    nhold = np.ones((S, C), np.int64)
    nkind = np.zeros((S, C), np.int64)
    ncyc = np.array([len(sc) for sc in schedules], np.int64)
    kidx = {kd: i for i, kd in enumerate(nemeses)}
    for s, sc in enumerate(schedules):
        prev_end = 0
        for c, (start, kd, hold) in enumerate(sc):
            nwait[s, c] = max(1, int(start) - prev_end)
            nhold[s, c] = max(1, int(hold))
            nkind[s, c] = kidx[kd]
            prev_end = prev_end + nwait[s, c] + 2 * NEM_APPLY_NS \
                + nhold[s, c]
    return nwait, nhold, nkind, ncyc


def _p_timeout(config: BatchConfig, kind: str) -> float:
    """Per-kind in-window timeout probability with the guided knobs
    folded in (defaults leave the pre-knob values bit-identical)."""
    p = P_TIMEOUT.get(kind, P_TIMEOUT_DEFAULT)
    if kind == "latency" and config.latency_ms is not None:
        p = min(0.9, p * config.latency_ms / 40.0)
    return min(1.0, p + config.drop_prob)


def _draws(config: BatchConfig, seeds) -> dict:
    """Pre-draw every random block, one independent generator per seed.

    Draw ORDER and SHAPES are part of the epoch: they depend only on
    the config, never on simulation outcomes, so per-seed streams stay
    aligned and histories stay pure functions of (seed, config). The
    stale-read block is always drawn (even when injection is off) so
    the knob cannot shift any other draw; likewise the nemesis blocks
    are drawn even when an explicit ``nem_schedule`` replaces them.
    """
    L, O = config.lanes, config.ops_per_lane
    ncy = NEM_CYCLES
    gap_ns = max(1_000_000, int(config.lanes * 1e9 / config.rate))
    # rough per-lane span drives nemesis cycle spacing
    span = schedule_span(config)
    w_lo, w_hi = max(1, span // (3 * ncy)), max(2, span // (2 * ncy))
    cols = {k: [] for k in ("start", "fsel", "wval", "cold", "cnew",
                            "lat", "gap", "tmo", "stale",
                            "nwait", "nhold", "nkind")}
    nnem = max(1, len(config.nemeses))
    for sd in seeds:
        rng = np.random.default_rng(int(sd))
        cols["start"].append(rng.integers(0, gap_ns, L))
        cols["fsel"].append(rng.integers(0, 2, (L, O)))
        cols["wval"].append(rng.integers(0, 5, (L, O)))
        cols["cold"].append(rng.integers(0, 5, (L, O)))
        cols["cnew"].append(rng.integers(0, 5, (L, O)))
        cols["lat"].append(rng.integers(1_000_000, 5_000_000, (L, O)))
        cols["gap"].append(rng.integers(gap_ns // 2,
                                        gap_ns + gap_ns // 2, (L, O)))
        cols["tmo"].append(rng.random((L, O)))
        cols["stale"].append(rng.random((L, O)))
        cols["nwait"].append(rng.integers(w_lo, w_hi, ncy))
        cols["nhold"].append(rng.integers(w_lo, w_hi, ncy))
        cols["nkind"].append(rng.integers(0, nnem, ncy))
    return {k: np.stack(v) for k, v in cols.items()}


# draw-plane rows of the folded (R, S, L, O) per-op stack
_CF, _CWV, _CCO, _CCN, _CLAT, _CGAP, _CPKI, _CVAI, _CVBI, _CTMO, \
    _CSTALE = range(11)

# the invoke-row slice gathered per step for the NEXT op
_INV_PLANES = np.array([_CF, _CPKI, _CVAI, _CVBI, _CLAT])[:, None]
_IF, _IPKI, _IVAI, _IVBI, _ILAT = range(5)


def generate(config: BatchConfig, seeds, nem_schedules=None) -> dict:
    """Run S seeds' simulations in lockstep; return their histories
    born columnar.

    ``nem_schedules`` (optional, one explicit window list per seed)
    overrides the drawn nemesis cycles per lane — the shrinker re-runs
    a whole candidate population in ONE call by repeating the failing
    seed across lanes with a different candidate schedule each.

    Returns ``{"histories": [History per seed], "epoch": "epoch-v2",
    "seeds": [...], "events": int, "steps": int, "compactions": int}``.
    """
    seeds = [int(s) for s in seeds]
    S = len(seeds)
    if S == 0:
        return {"histories": [], "epoch": GEN_EPOCH_V2, "seeds": [],
                "events": 0, "steps": 0, "compactions": 0}
    L, O, K = config.lanes, config.ops_per_lane, config.keys
    NL = L  # nemesis lane id (time residue); L <= STRIDE - 2
    is_register = config.workload == "register"
    has_nem = bool(config.nemeses)
    inject_stale = config.inject_stale_reads
    # stale reads are replica-staleness: with faults configured they
    # fire only inside an open partition window (see STALE_P)
    part_idx = (config.nemeses.index("partition")
                if "partition" in config.nemeses else -2)
    d = _draws(config, seeds)
    AR = np.arange(S)

    # lane roles: first `readers` lanes read-only, the rest write
    readers = config.readers
    key_of_lane = (np.arange(L, dtype=np.int64) % K if is_register
                   else np.full(L, -1, np.int64))
    if is_register:
        # readers: f=read; writers alternate write/cas by fsel
        fop = np.where(np.arange(L)[None, :, None] < readers,
                       FC_READ, FC_WRITE + d["fsel"])
        pki = np.where(fop == FC_READ, PK_REG_RD_INV,
                       np.where(fop == FC_WRITE, PK_REG_WR_INV,
                                PK_REG_CAS_INV))
        vai = np.where(fop == FC_WRITE, d["wval"],
                       np.where(fop == FC_CAS, d["cold"], -1))
        vbi = np.where(fop == FC_CAS, d["cnew"], -1)
    else:
        fop = np.where(np.arange(L)[None, :, None] < readers,
                       FC_SRD, FC_ADD)
        # per-seed-unique add values: op_index * writers + writer_rank
        wrank = np.arange(L, dtype=np.int64) - readers  # <0 for readers
        nwriters = L - readers
        addval = (np.arange(O, dtype=np.int64)[None, None, :] * nwriters
                  + np.where(wrank < 0, 0, wrank)[None, :, None])
        pki = np.where(fop == FC_ADD, PK_SET_ADD, PK_SET_RD_INV)
        vai = np.where(fop == FC_ADD, addval, -1)
        vbi = np.full_like(vai, -1)
    planes = [fop, d["wval"], d["cold"], d["cnew"],
              d["lat"] * STRIDE, d["gap"] * STRIDE, pki, vai, vbi,
              (d["tmo"] * 1e9).astype(np.int64),
              (d["stale"] < STALE_P).astype(np.int64)]
    CL = np.stack([np.broadcast_to(p, (S, L, O)) for p in planes])
    p_by_kind = (np.array(
        [_p_timeout(config, kd) for kd in config.nemeses]
        or [0.0]) * 1e9).astype(np.int64)
    if nem_schedules is not None:
        if len(nem_schedules) != S:
            raise ValueError("nem_schedules must align with seeds "
                             f"({len(nem_schedules)} != {S})")
        scheds = [_norm_schedule(sc, config.nemeses) or ()
                  for sc in nem_schedules]
    elif config.nem_schedule is not None:
        scheds = [config.nem_schedule] * S
    else:
        scheds = None
    if has_nem and scheds is not None:
        nw, nh, nkind, n_cycles = _schedule_arrays(scheds,
                                                   config.nemeses)
        nwaitE = nw * STRIDE
        nholdE = nh * STRIDE
        ncyc_cap = nkind.shape[1]
    else:
        nwaitE = d["nwait"] * STRIDE
        nholdE = d["nhold"] * STRIDE
        nkind = d["nkind"]
        n_cycles = np.full(S, NEM_CYCLES, np.int64)
        ncyc_cap = NEM_CYCLES
    nem_apply = NEM_APPLY_NS * STRIDE
    nfb = config.nem_f_base()

    # lane residues make per-seed event times unique, so the heap can
    # skip epoch-ordinal bookkeeping (identical results, cheaper steps)
    heap = BatchHeap(S, capacity=NL + 1, epoch=GEN_EPOCH_V2,
                     unique_times=True)

    # per-seed machine state
    opi = np.zeros((S, L), np.int64)       # op index in flight per lane
    retire = np.zeros((S, L), np.int64)    # info-retirement count
    done_lanes = np.zeros(S, np.int64)
    ver = np.zeros((S, K), np.int64)
    val = np.full((S, K), -1, np.int64)    # -1 encodes "never written"
    pver = np.zeros((S, K), np.int64)      # pre-last-write snapshot
    pval = np.full((S, K), -1, np.int64)   # (stale-read injection)
    nphase = np.zeros(S, np.int64)         # 0..3 nemesis phase
    ncyci = np.zeros(S, np.int64)          # completed fault cycles
    win_active = np.zeros(S, bool)
    win_p = np.zeros(S, np.int64)
    win_kind = np.full(S, -1, np.int64)    # open window's fault index
    applied = [[] for _ in range(S)]       # set workload: sorted adds
    snaps = [[] for _ in range(S)]         # set workload: read snaps

    e_time, e_tc, e_fc, e_proc, e_key = [], [], [], [], []
    e_pk, e_va, e_vb, e_vc, e_act = [], [], [], [], []
    steps = 0

    # shared constant rows (append-only; never written after creation)
    ALL = np.ones(S, bool)
    ZERO = np.zeros(S, np.int64)
    NEG1 = np.full(S, -1, np.int64)
    K_CMP = np.full(S, KIND_COMPLETE, np.int64)

    # op 0 invoke rows are emitted upfront (pure draws); the heap is
    # seeded with each lane's FIRST completion
    startE = d["start"] * STRIDE + np.arange(L)
    latE = CL[_CLAT]
    for j0 in range(L):
        e_time.append(startE[:, j0])
        e_tc.append(ZERO)
        e_fc.append(CL[_CF][:, j0, 0])
        e_proc.append(np.full(S, j0, np.int64))
        e_key.append(np.full(S, key_of_lane[j0], np.int64))
        e_pk.append(CL[_CPKI][:, j0, 0])
        e_va.append(CL[_CVAI][:, j0, 0])
        e_vb.append(CL[_CVBI][:, j0, 0])
        e_vc.append(NEG1)
        e_act.append(ALL)
        heap.push(startE[:, j0] + latE[:, j0, 0], j0, KIND_COMPLETE)
    if has_nem:
        # explicit empty schedules leave those seeds fault-free
        heap.push(nwaitE[:, 0] + NL, NL, KIND_NEM, n_cycles > 0)

    while True:
        t, kind, lane, act = heap.pop_min()
        if not act.any():
            break
        steps += 1
        if has_nem:
            m_cmp = act & (kind == KIND_COMPLETE)
            m_nem = act & ~m_cmp
            # client-lane index for gathers; nemesis/inactive rows
            # alias lane 0 and are masked out or overwritten below
            j = np.where(m_cmp, lane, 0)
        else:
            m_cmp = act
            j = np.where(act, lane, 0)
        oi = opi[AR, j]
        g = CL[:, AR, j, oi]            # ONE slab: all per-op draws
        f = g[_CF]
        ret = retire[AR, j]
        row_tc = np.zeros(S, np.int64)
        row_fc = f
        row_proc = j + ret * L
        row_key = key_of_lane[j]
        row_pk = np.zeros(S, np.int64)
        row_va = np.full(S, -1, np.int64)
        row_vb = np.full(S, -1, np.int64)
        row_vc = NEG1
        row_act = act

        # -- completions: timeout infos vs real outcomes --------------
        if has_nem:
            m_to = m_cmp & win_active & (g[_CTMO] < win_p)
            m_ok = m_cmp & ~m_to
            if m_to.any():
                row_tc[m_to] = TC_INFO
                row_pk[m_to] = g[_CPKI][m_to]
                row_va[m_to] = g[_CVAI][m_to]
                row_vb[m_to] = g[_CVBI][m_to]
                retire[AR[m_to], j[m_to]] += 1
                ret = ret + m_to  # later ops (incl. this step's
                # eagerly-emitted next invoke) use the retired proc
        else:
            m_ok = m_cmp

        if is_register:
            m_r = m_ok & (f == FC_READ)
            m_w = m_ok & (f == FC_WRITE)
            m_c = m_ok & (f == FC_CAS)
            if m_r.any():
                sr, kr = AR[m_r], row_key[m_r]
                rv, rl = ver[sr, kr], val[sr, kr]
                if inject_stale:
                    stale_m = g[_CSTALE][m_r] == 1
                    if has_nem:
                        stale_m &= (win_active[m_r]
                                    & (win_kind[m_r] == part_idx))
                    rv = np.where(stale_m, pver[sr, kr], rv)
                    rl = np.where(stale_m, pval[sr, kr], rl)
                row_tc[m_r] = TC_OK
                row_pk[m_r] = PK_REG_RD_OK
                row_va[m_r] = rv
                row_vb[m_r] = rl
            if m_w.any():
                sw, kw = AR[m_w], row_key[m_w]
                wv = g[_CWV][m_w]
                pver[sw, kw] = ver[sw, kw]
                pval[sw, kw] = val[sw, kw]
                nv = ver[sw, kw] + 1
                ver[sw, kw] = nv
                val[sw, kw] = wv
                row_tc[m_w] = TC_OK
                row_pk[m_w] = PK_REG_WR_OK
                row_va[m_w] = nv
                row_vb[m_w] = wv
            if m_c.any():
                sc, kc = AR[m_c], row_key[m_c]
                co, cn = g[_CCO][m_c], g[_CCN][m_c]
                okc = val[sc, kc] == co
                scw, kcw = sc[okc], kc[okc]
                pver[scw, kcw] = ver[scw, kcw]
                pval[scw, kcw] = val[scw, kcw]
                nv2 = ver[scw, kcw] + 1
                ver[scw, kcw] = nv2
                val[scw, kcw] = cn[okc]
                row_tc[m_c] = np.where(okc, TC_OK, TC_FAIL)
                row_pk[m_c] = np.where(okc, PK_REG_CAS_OK,
                                       PK_REG_CAS_FAIL)
                va_c = co.copy()
                va_c[okc] = nv2
                row_va[m_c] = va_c
                row_vb[m_c] = np.where(okc, co, cn)
                row_vc = row_vc.copy()
                row_vc[m_c] = np.where(okc, cn, -1)
        else:
            m_a = m_ok & (f == FC_ADD)
            m_s = m_ok & (f == FC_SRD)
            if m_a.any():
                av = g[_CVAI]
                row_tc[m_a] = TC_OK
                row_pk[m_a] = PK_SET_ADD
                row_va[m_a] = av[m_a]
                for s in np.flatnonzero(m_a).tolist():
                    insort(applied[s], int(av[s]))
            if m_s.any():
                row_tc[m_s] = TC_OK
                row_pk[m_s] = PK_SET_RD_OK
                for s in np.flatnonzero(m_s).tolist():
                    snaps[s].append(list(applied[s]))
                    row_va[s] = len(snaps[s]) - 1

        # -- advance lanes; eagerly emit the NEXT op's invoke row -----
        ncur = oi + 1
        m_adv = m_cmp & (ncur < O)
        opi[AR[m_adv], j[m_adv]] = ncur[m_adv]
        oi2 = oi + m_adv                 # clamped: non-adv rows inert
        g2 = CL[_INV_PLANES, AR, j, oi2]
        inv_t = t + g[_CGAP]
        inv_proc = j + ret * L
        nxt_push = m_adv
        nxt_t = inv_t + g2[_ILAT]
        nxt_kind = K_CMP
        push_lane = j

        # -- nemesis lane: 4-phase fault/heal windows -----------------
        if has_nem:
            done_lanes += m_cmp & (ncur >= O)
        if has_nem and m_nem.any():
            ph = nphase
            ci = np.minimum(ncyci, ncyc_cap - 1)
            nk = nkind[AR, ci]
            m_n0 = m_nem & (ph == 0)
            m_die = m_n0 & (done_lanes >= L)  # clients done: no window
            m_emit = m_nem & ~m_die
            row_act = act.copy()
            row_act[m_die] = False
            m_sinv = m_n0 & ~m_die
            m_sok = m_nem & (ph == 1)
            m_einv = m_nem & (ph == 2)
            m_eok = m_nem & (ph == 3)
            is_stop = (m_einv | m_eok).astype(np.int64)
            nf = nfb + 2 * nk + is_stop
            row_fc = np.where(m_emit, nf, row_fc)
            row_tc[m_sok | m_eok] = TC_INFO  # invokes keep default 0
            row_proc[m_emit] = -1
            row_key[m_emit] = -1
            row_pk[m_emit] = PK_NEM
            row_va[m_emit] = nk[m_emit]
            row_vb[m_emit] = is_stop[m_emit]
            win_active = (win_active | m_sok) & ~m_eok
            win_p[m_sok] = p_by_kind[nk[m_sok]]
            win_kind[m_sok] = nk[m_sok]
            win_kind[m_eok] = -1
            ncyci = ncyci + m_eok
            nphase = np.where(m_emit, (ph + 1) % 4, nphase)
            n_push = m_emit & ~(m_eok & (ncyci >= n_cycles))
            ci2 = np.minimum(ncyci, ncyc_cap - 1)
            ntm = np.where(m_sinv | m_einv, t + nem_apply,
                           np.where(m_sok, t + nholdE[AR, ci],
                                    t + nwaitE[AR, ci2]))
            nxt_push = nxt_push | n_push
            nxt_t = np.where(n_push, ntm, nxt_t)
            nxt_kind = np.where(n_push, KIND_NEM, nxt_kind)
            push_lane = np.where(m_nem, NL, push_lane)

        heap.push_slots(nxt_t, push_lane, nxt_kind, nxt_push)

        # completion (or nemesis) row at t ...
        e_time.append(t)
        e_tc.append(row_tc)
        e_fc.append(row_fc)
        e_proc.append(row_proc)
        e_key.append(row_key)
        e_pk.append(row_pk)
        e_va.append(row_va)
        e_vb.append(row_vb)
        e_vc.append(row_vc)
        e_act.append(row_act)
        # ... and the next op's invoke row at its later timestamp (the
        # finish-phase per-seed argsort restores global time order)
        e_time.append(inv_t)
        e_tc.append(ZERO)
        e_fc.append(g2[_IF])
        e_proc.append(inv_proc)
        e_key.append(row_key)
        e_pk.append(g2[_IPKI])
        e_va.append(g2[_IVAI])
        e_vb.append(g2[_IVBI])
        e_vc.append(NEG1)
        e_act.append(m_adv)

    histories, events = _finish(config, seeds, e_time, e_tc, e_fc,
                                e_proc, e_key, e_pk, e_va, e_vb, e_vc,
                                e_act, snaps)
    return {"histories": histories, "epoch": GEN_EPOCH_V2,
            "seeds": seeds, "events": events, "steps": steps,
            "compactions": heap.compactions}


def _nem_start_value(config, kind):
    """Start-op :info value for a fault kind, with the guided mutation
    knobs (partition shape, latency delta) folded in."""
    if kind == "partition" and config.partition_shape:
        return config.partition_shape
    if kind == "latency" and config.latency_ms is not None:
        return {"delta-ms": config.latency_ms,
                "jitter-ms": round(config.latency_ms / 5.0, 3)}
    return NEM_START_VALUE.get(kind, "all")


def _finish(config, seeds, e_time, e_tc, e_fc, e_proc, e_key, e_pk,
            e_va, e_vb, e_vc, e_act, snaps):
    """Gather each seed's rows (sorted by its unique event times) into
    an OpColumns-backed History."""
    S = len(seeds)
    f_table = config.f_table()
    key_table = ([config.key_offset + i for i in range(config.keys)]
                 if config.workload == "register" else [])
    proc_table = ["nemesis"]
    nem_start = [_nem_start_value(config, kd)
                 for kd in config.nemeses] or [None]
    if not e_tc:
        empty = np.zeros(0, np.int64)
        return [History.from_columns(OpColumns(
            empty.astype(np.int8), empty.astype(np.int32), empty,
            empty, empty, empty, [], {}, {}, f_table, key_table,
            proc_table)) for _ in range(S)], 0
    TM, TC = np.stack(e_time), np.stack(e_tc)
    FC, PR, KID = np.stack(e_fc), np.stack(e_proc), np.stack(e_key)
    PK, VA, VB = np.stack(e_pk), np.stack(e_va), np.stack(e_vb)
    VC, ACT = np.stack(e_vc), np.stack(e_act)
    events = int(ACT.sum())
    out = []
    for s in range(S):
        rows = np.flatnonzero(ACT[:, s])
        tm = TM[rows, s]
        rows = rows[np.argsort(tm)]  # unique times: total order
        n = rows.size
        tc = TC[rows, s]
        pk_l = PK[rows, s].tolist()
        va_l = VA[rows, s].tolist()
        vb_l = VB[rows, s].tolist()
        vc_l = VC[rows, s].tolist()
        tc_l = tc.tolist()
        snap = snaps[s]
        values = [None] * n
        extras: dict = {}
        for i in range(n):
            p = pk_l[i]
            if p == PK_REG_RD_INV:
                values[i] = [None, None]
            elif p == PK_REG_RD_OK:
                v = vb_l[i]
                values[i] = [va_l[i], None if v < 0 else v]
            elif p == PK_REG_WR_INV:
                values[i] = [None, va_l[i]]
            elif p == PK_REG_WR_OK:
                values[i] = [va_l[i], vb_l[i]]
            elif p == PK_REG_CAS_INV:
                values[i] = [None, [va_l[i], vb_l[i]]]
            elif p == PK_REG_CAS_OK:
                values[i] = [va_l[i], [vb_l[i], vc_l[i]]]
            elif p == PK_REG_CAS_FAIL:
                values[i] = [None, [va_l[i], vb_l[i]]]
                extras[i] = {"error": "did-not-succeed"}
            elif p == PK_SET_ADD:
                values[i] = va_l[i]
            elif p == PK_SET_RD_OK:
                values[i] = snap[va_l[i]]
            elif p == PK_NEM:
                values[i] = None if vb_l[i] else nem_start[va_l[i]]
            # PK_SET_RD_INV: value stays None
            if tc_l[i] == TC_INFO and p != PK_NEM:
                extras[i] = {"error": "timeout"}
        cols = OpColumns(
            tc.astype(np.int8), FC[rows, s].astype(np.int32),
            PR[rows, s], KID[rows, s], TM[rows, s] // STRIDE,
            np.arange(n, dtype=np.int64), values, extras, {},
            f_table, key_table, proc_table)
        out.append(History.from_columns(cols))
    return out, events


def generate_for_opts(opts: dict, seeds) -> dict:
    """Campaign/bench entry: opts→config mapping plus generate."""
    return generate(BatchConfig.from_opts(opts), seeds)
