"""Jitted device-side batched generator: generator epoch-v3.

Where ``engine.py`` (epoch-v2) advances S seeds in lockstep but still
pays one host-side numpy step per event row, this engine puts the step
function on the device. The move that makes that possible: for the
register/set workloads the *timing* of every event is a pure function
of the draws — ``inv[j, i+1] = cmp[j, i] + gap``, ``cmp[j, i] =
inv[j, i] + lat``, nemesis cycles convert to absolute windows exactly
as ``_mvcc_schedule`` does, and the phase-0 death check reduces to
``t0 <= max_fin`` — so the BatchHeap's pop sequence materializes as a
precomputed drain order before the loop ever runs. What remains
genuinely sequential is the register client state machine (version
chains and CAS outcomes feed back into later ops), and exactly that
runs as ONE ``jax.lax.scan`` over device arrays: the scan carry is the
lane-packed SoA machine state (per-key ``ver``/``val`` plus the
stale-snapshot ``pver``/``pval``), each step pops the next completion
of every seed simultaneously (the heap drain, vectorized over S), and
no host dispatch happens per iteration — JAX001-004 clean by
construction, no suppressions.

Determinism contract (generator epoch-v3; see the epoch ledger in
runner/sim.py):

- Per-seed histories are a pure function of ``(seed, BatchConfig)``.
  Every random block derives from ``jax.random`` (threefry) under a
  per-seed ``PRNGKey(seed mod 2**32)``, split once into a fixed list
  of subkeys — draw ORDER, SHAPES and dtypes are part of the epoch and
  depend only on the config. Histories therefore differ from epoch-v2
  op-by-op (different draw source — the point of declaring an epoch);
  verdicts must not, and the cross-epoch fuzz pins that against BOTH
  epoch-v1 and epoch-v2.
- Event ordering keeps epoch-v2's rule unchanged: times carry the lane
  residue (``time = t_ns * STRIDE + lane``), so per-seed event times
  are unique and the drain order is total. Timeout semantics, the
  in-window probability table, stale-read gating to open partition
  windows, the nemesis 4-phase machine (including explicit
  ``nem_schedule`` replay through the same ``_schedule_arrays``
  clamps) all mirror epoch-v2 bit-for-bit *in structure*; only the
  draw values differ.
- The four MVCC consistency-surface workloads delegate to the
  epoch-v2 per-seed sweep unchanged (their machines carry rich Python
  state and their rows are declared identical across v2/v3): within
  epoch-v3 they are bit-identical to the epoch-v2 histories of the
  same (seed, config), which keeps the injection soundness arguments
  and their golden pins intact.

Integer draws with statically small ranges come from
``jax.random.randint`` (int32); wide ranges (lane start offsets, gaps,
nemesis wait/hold — up to ~1e9 ns and beyond int32 after scaling) come
from ``jax.random.uniform`` float32 scaled on the host in float64.
Device arrays stay int32/float32 throughout (no x64 requirement); all
ns arithmetic happens host-side in int64.
"""

from __future__ import annotations

from bisect import insort
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .engine import (
    FC_ADD, FC_CAS, FC_READ, FC_SRD, FC_WRITE, MVCC_WORKLOADS,
    NEM_APPLY_NS, NEM_CYCLES, PK_NEM, PK_REG_CAS_FAIL, PK_REG_CAS_INV,
    PK_REG_CAS_OK, PK_REG_RD_INV, PK_REG_RD_OK, PK_REG_WR_INV,
    PK_REG_WR_OK, PK_SET_ADD, PK_SET_RD_INV, PK_SET_RD_OK, STALE_P,
    STRIDE, TC_FAIL, TC_INFO, TC_INVOKE, TC_OK, BatchConfig,
    _draws_shape_params, _finish, _generate_mvcc, _norm_schedule,
    _p_timeout, _schedule_arrays,
)
from .heap import EPOCH_V3

GEN_EPOCH_V3 = EPOCH_V3

_N_SUBKEYS = 12  # fixed split order below; part of the epoch


@partial(jax.jit, static_argnums=(1, 2, 3, 4))
def _draw_device(seeds_u32, L, O, ncy, nnem):
    """All per-seed random blocks in ONE device dispatch, vmapped over
    seeds. Subkey index == draw block (the epoch's draw order):
    0 start, 1 fsel, 2 wval, 3 cold, 4 cnew, 5 lat, 6 gap, 7 tmo,
    8 stale, 9 nwait, 10 nhold, 11 nkind."""
    def one(seed):
        ks = jax.random.split(jax.random.PRNGKey(seed), _N_SUBKEYS)
        return (
            jax.random.uniform(ks[0], (L,), jnp.float32),
            jax.random.randint(ks[1], (L, O), 0, 2, jnp.int32),
            jax.random.randint(ks[2], (L, O), 0, 5, jnp.int32),
            jax.random.randint(ks[3], (L, O), 0, 5, jnp.int32),
            jax.random.randint(ks[4], (L, O), 0, 5, jnp.int32),
            jax.random.randint(ks[5], (L, O), 1_000_000, 5_000_000,
                               jnp.int32),
            jax.random.uniform(ks[6], (L, O), jnp.float32),
            jax.random.uniform(ks[7], (L, O), jnp.float32),
            jax.random.uniform(ks[8], (L, O), jnp.float32),
            jax.random.uniform(ks[9], (ncy,), jnp.float32),
            jax.random.uniform(ks[10], (ncy,), jnp.float32),
            jax.random.randint(ks[11], (ncy,), 0, nnem, jnp.int32),
        )

    return jax.vmap(one)(seeds_u32)


def _scale_int(u, lo, hi):
    """Uniform float32 block -> integers in [lo, hi) (host float64
    math; the scaled-uniform distribution is the epoch's declared draw
    for wide ranges)."""
    lo, hi = int(lo), int(hi)
    v = lo + (np.asarray(u, np.float64) * float(hi - lo)).astype(np.int64)
    return np.minimum(v, hi - 1)


def _draws_jax(config: BatchConfig, seeds) -> dict:
    """Epoch-v3 draw blocks as host int64/float64 numpy, same keys and
    shapes as engine._draws — one device dispatch for the whole batch."""
    L, O, ncy, nnem, gap_ns, w_lo, w_hi = _draws_shape_params(config)
    seeds_u32 = np.asarray([int(s) & 0xFFFFFFFF for s in seeds],
                           np.uint32)
    blocks = _draw_device(seeds_u32, L, O, ncy, nnem)
    (start_u, fsel, wval, cold, cnew, lat, gap_u, tmo, stale,
     nwait_u, nhold_u, nkind) = [np.asarray(b) for b in blocks]
    return {
        "start": _scale_int(start_u, 0, gap_ns),
        "fsel": fsel.astype(np.int64),
        "wval": wval.astype(np.int64),
        "cold": cold.astype(np.int64),
        "cnew": cnew.astype(np.int64),
        "lat": lat.astype(np.int64),
        "gap": _scale_int(gap_u, gap_ns // 2, gap_ns + gap_ns // 2),
        "tmo": tmo.astype(np.float64),
        "stale": stale.astype(np.float64),
        "nwait": _scale_int(nwait_u, w_lo, w_hi),
        "nhold": _scale_int(nhold_u, w_lo, w_hi),
        "nkind": nkind.astype(np.int64),
    }


def default_schedule_jax(config: BatchConfig, seed: int) -> list:
    """Epoch-v3 analog of engine.default_schedule: the DRAWN nemesis
    plan of ``(config, seed)`` as an explicit window list whose replay
    through ``nem_schedules`` is bit-identical to the drawn run (same
    inverse arithmetic as the epoch-v2 pin)."""
    if not config.nemeses:
        return []
    d = _draws_jax(config, [int(seed)])
    out, tcur = [], 0
    for c in range(NEM_CYCLES):
        start = tcur + int(d["nwait"][0, c])
        hold = int(d["nhold"][0, c])
        out.append((start, config.nemeses[int(d["nkind"][0, c])], hold))
        tcur = start + 2 * NEM_APPLY_NS + hold
    return out


@jax.jit
def _drain_register(ver0, val0, pver0, pval0, k_seq, f_seq, wv_seq,
                    co_seq, cn_seq, to_seq, sg_seq):
    """The jitted heap drain: one ``lax.scan`` step per completion,
    every seed advanced simultaneously (the lockstep cadence, on
    device). Inputs are the drain-order op planes transposed to
    ``(N, S)``; the carry is the lane-packed register machine state.
    Timed-out ops (``to_seq``) leave the machine untouched — the host
    overlays their info rows from the invoke planes afterwards."""
    S = ver0.shape[0]
    AR = jnp.arange(S)

    def body(carry, x):
        ver, val, pver, pval = carry
        k, f, wv, co, cn, to, sg = x
        cv = ver[AR, k]
        cl = val[AR, k]
        ok = ~to
        is_r = ok & (f == FC_READ)
        is_w = ok & (f == FC_WRITE)
        is_c = ok & (f == FC_CAS)
        rd_stale = sg & is_r
        rv = jnp.where(rd_stale, pver[AR, k], cv)
        rl = jnp.where(rd_stale, pval[AR, k], cl)
        cas_ok = is_c & (cl == co)
        wr = is_w | cas_ok
        nv = cv + 1
        nl = jnp.where(is_w, wv, cn)
        pver = pver.at[AR, k].set(jnp.where(wr, cv, pver[AR, k]))
        pval = pval.at[AR, k].set(jnp.where(wr, cl, pval[AR, k]))
        ver = ver.at[AR, k].set(jnp.where(wr, nv, cv))
        val = val.at[AR, k].set(jnp.where(wr, nl, cl))
        tc = jnp.where(is_c & ~cas_ok, np.int32(TC_FAIL),
                       np.int32(TC_OK))
        pk = jnp.where(is_r, PK_REG_RD_OK,
                       jnp.where(is_w, PK_REG_WR_OK,
                                 jnp.where(cas_ok, PK_REG_CAS_OK,
                                           PK_REG_CAS_FAIL)))
        va = jnp.where(is_r, rv,
                       jnp.where(is_w, nv, jnp.where(cas_ok, nv, co)))
        vb = jnp.where(is_r, rl,
                       jnp.where(is_w, wv, jnp.where(cas_ok, co, cn)))
        vc = jnp.where(cas_ok, cn, np.int32(-1))
        return (ver, val, pver, pval), (tc, pk, va, vb, vc)

    _, ys = jax.lax.scan(body, (ver0, val0, pver0, pval0),
                         (k_seq, f_seq, wv_seq, co_seq, cn_seq,
                          to_seq, sg_seq))
    return ys


def _windows_abs(config, d, scheds, S, max_fin):
    """Per-seed nemesis cycles as absolute lane-residue times plus the
    fire mask — the phase machine flattened: t0 start-invoke, t1
    start-ok (window opens), t2 stop-invoke, t3 stop-ok (window
    closes). A cycle fires iff it is within the seed's cycle count and
    its t0 lands before the last client completion (the lockstep death
    check, ``done_lanes >= L`` at phase-0 pop, reduced to absolute
    time)."""
    if scheds is not None:
        nw, nh, nkind, n_cycles = _schedule_arrays(scheds,
                                                   config.nemeses)
    else:
        nw, nh = d["nwait"], d["nhold"]
        nkind = d["nkind"]
        n_cycles = np.full(S, NEM_CYCLES, np.int64)
    C = nkind.shape[1] if nkind.ndim == 2 else nkind.shape[0]
    nw = nw.reshape(S, C)
    nh = nh.reshape(S, C)
    nkind = nkind.reshape(S, C)
    NL = config.lanes
    apply_i = NEM_APPLY_NS * STRIDE
    period = nw + 2 * NEM_APPLY_NS + nh
    end_cum = np.cumsum(period, axis=1)
    st = end_cum - 2 * NEM_APPLY_NS - nh    # st[c] = prev_end + nw[c]
    t0 = st * STRIDE + NL
    t1 = t0 + apply_i
    t2 = t1 + nh * STRIDE
    t3 = t2 + apply_i
    fires = ((np.arange(C)[None, :] < n_cycles[:, None])
             & (t0 <= max_fin[:, None]))
    return t0, t1, t2, t3, nkind, fires


def generate_jax(config: BatchConfig, seeds, nem_schedules=None) -> dict:
    """Epoch-v3 generate(): same return shape as engine.generate, with
    the drain on device. MVCC workloads delegate to the per-seed sweep
    (rows identical to epoch-v2 by declaration)."""
    seeds = [int(s) for s in seeds]
    S = len(seeds)
    if S == 0:
        return {"histories": [], "epoch": GEN_EPOCH_V3, "seeds": [],
                "events": 0, "steps": 0, "compactions": 0}
    if config.workload in MVCC_WORKLOADS:
        out = _generate_mvcc(config, seeds, nem_schedules)
        out["epoch"] = GEN_EPOCH_V3
        return out
    L, O, K = config.lanes, config.ops_per_lane, config.keys
    N = L * O
    is_register = config.workload == "register"
    has_nem = bool(config.nemeses)
    inject_stale = config.inject_stale_reads
    part_idx = (config.nemeses.index("partition")
                if "partition" in config.nemeses else -2)
    d = _draws_jax(config, seeds)

    # -- per-op planes (identical role arithmetic to the v2 engine) ---
    readers = config.readers
    lane_col = np.arange(L)[None, :, None]
    key_of_lane = (np.arange(L, dtype=np.int64) % K if is_register
                   else np.full(L, -1, np.int64))
    if is_register:
        fop = np.where(lane_col < readers, FC_READ,
                       FC_WRITE + d["fsel"])
        pki = np.where(fop == FC_READ, PK_REG_RD_INV,
                       np.where(fop == FC_WRITE, PK_REG_WR_INV,
                                PK_REG_CAS_INV))
        vai = np.where(fop == FC_WRITE, d["wval"],
                       np.where(fop == FC_CAS, d["cold"], -1))
        vbi = np.where(fop == FC_CAS, d["cnew"], -1)
    else:
        fop = np.where(lane_col < readers, FC_SRD, FC_ADD)
        wrank = np.arange(L, dtype=np.int64) - readers
        nwriters = L - readers
        addval = (np.arange(O, dtype=np.int64)[None, None, :] * nwriters
                  + np.where(wrank < 0, 0, wrank)[None, :, None])
        pki = np.where(fop == FC_ADD, PK_SET_ADD, PK_SET_RD_INV)
        vai = np.where(fop == FC_ADD, addval, -1)
        vbi = np.full_like(vai, -1)
    fop = np.broadcast_to(fop, (S, L, O))
    pki = np.broadcast_to(pki, (S, L, O))
    vai = np.broadcast_to(vai, (S, L, O))
    vbi = np.broadcast_to(vbi, (S, L, O))

    # -- the timeline: cumulative sums, not a step loop ---------------
    lat, gap = d["lat"], d["gap"]
    step_ns = lat + gap
    inv = (d["start"][:, :, None]
           + (np.cumsum(step_ns, axis=2) - step_ns))
    cmp_ = inv + lat
    res = np.arange(L, dtype=np.int64)[None, :, None]
    inv_i = inv * STRIDE + res
    cmp_i = cmp_ * STRIDE + res

    # -- nemesis windows as precomputed masks -------------------------
    if nem_schedules is not None:
        if len(nem_schedules) != S:
            raise ValueError("nem_schedules must align with seeds "
                             f"({len(nem_schedules)} != {S})")
        scheds = [_norm_schedule(sc, config.nemeses) or ()
                  for sc in nem_schedules]
    elif config.nem_schedule is not None:
        scheds = [config.nem_schedule] * S
    else:
        scheds = None
    TO = np.zeros((S, L, O), bool)
    part_open = np.zeros((S, L, O), bool)
    nem_blocks = None
    if has_nem:
        max_fin = cmp_i[:, :, -1].max(axis=1)
        t0, t1, t2, t3, nkind, fires = _windows_abs(
            config, d, scheds, S, max_fin)
        C = nkind.shape[1]
        p9_kind = (np.array([_p_timeout(config, kd)
                             for kd in config.nemeses]) * 1e9
                   ).astype(np.int64)
        p9k = p9_kind[nkind]                       # (S, C)
        tmo9 = (d["tmo"] * 1e9).astype(np.int64)
        for c in range(C):
            in_w = (fires[:, c][:, None, None]
                    & (cmp_i > t1[:, c][:, None, None])
                    & (cmp_i < t3[:, c][:, None, None]))
            TO |= in_w & (tmo9 < p9k[:, c][:, None, None])
            if part_idx >= 0:
                part_open |= in_w & (nkind[:, c] == part_idx)[
                    :, None, None]
        # nemesis rows: 4 per fired cycle (start-inv/ok, stop-inv/ok)
        nem_t = np.stack([t0, t1, t2, t3], axis=2).reshape(S, 4 * C)
        is_stop = np.tile(np.array([0, 0, 1, 1], np.int64), C)[None, :]
        nem_tc = np.tile(np.array([TC_INVOKE, TC_INFO, TC_INVOKE,
                                   TC_INFO], np.int64), C)[None, :]
        nk4 = np.repeat(nkind, 4, axis=1)
        nem_blocks = {
            "time": nem_t,
            "tc": np.broadcast_to(nem_tc, (S, 4 * C)),
            "fc": config.nem_f_base() + 2 * nk4 + is_stop,
            "pk": np.full((S, 4 * C), PK_NEM, np.int64),
            "va": nk4,
            "vb": np.broadcast_to(is_stop, (S, 4 * C)),
            "act": np.repeat(fires, 4, axis=1),
        }
    if inject_stale:
        SG = d["stale"] < STALE_P
        if has_nem:
            SG &= part_open
    else:
        SG = np.zeros((S, L, O), bool)

    # -- retirement / proc columns (pure cumsums) ---------------------
    to_cum = np.cumsum(TO, axis=2)
    ret_excl = to_cum - TO                  # timeouts strictly before op
    proc = (np.arange(L)[None, :, None] + ret_excl * L)
    key_col = np.broadcast_to(key_of_lane[None, :, None], (S, L, O))

    # -- the device drain ---------------------------------------------
    order = np.argsort(cmp_i.reshape(S, N), axis=1)  # unique times
    flat = lambda a: a.reshape(S, N)
    take = lambda a: np.take_along_axis(flat(a), order, axis=1)
    snaps = [[] for _ in range(S)]
    if is_register:
        srt = {k: take(v) for k, v in (
            ("key", key_col), ("f", fop), ("wv", d["wval"]),
            ("co", d["cold"]), ("cn", d["cnew"]))}
        to_srt = take(TO)
        sg_srt = take(SG)
        dev = lambda a, dt: jnp.asarray(
            np.ascontiguousarray(a.T.astype(dt)))
        ys = _drain_register(
            jnp.zeros((S, K), jnp.int32),
            jnp.full((S, K), -1, jnp.int32),
            jnp.zeros((S, K), jnp.int32),
            jnp.full((S, K), -1, jnp.int32),
            dev(srt["key"], np.int32), dev(srt["f"], np.int32),
            dev(srt["wv"], np.int32), dev(srt["co"], np.int32),
            dev(srt["cn"], np.int32), dev(to_srt, bool),
            dev(sg_srt, bool))
        tc_o, pk_o, va_o, vb_o, vc_o = [np.asarray(y).T.astype(np.int64)
                                        for y in ys]
        unsrt = np.empty((S, N), np.int64)
        back = lambda a: (np.put_along_axis(unsrt, order, a, axis=1),
                          unsrt.copy())[1]
        tc_cmp, pk_cmp = back(tc_o), back(pk_o)
        va_cmp, vb_cmp, vc_cmp = back(va_o), back(vb_o), back(vc_o)
    else:
        # set workload: adds/reads have no cross-op feedback, so rows
        # are draw-determined; only the snapshot lists are sequential
        # (reconstructed below, exactly the v2 insort/copy semantics)
        f_srt = take(fop)
        to_srt = take(TO)
        va_srt = take(vai)
        tc_cmp = np.full((S, N), 1, np.int64)
        pk_cmp = np.where(flat(fop) == FC_ADD, PK_SET_ADD,
                          PK_SET_RD_OK)
        va_cmp = flat(vai).copy()
        vb_cmp = np.full((S, N), -1, np.int64)
        vc_cmp = np.full((S, N), -1, np.int64)
        rd_idx = np.full((S, N), -1, np.int64)
        for s in range(S):
            applied: list = []
            f_s = f_srt[s].tolist()
            to_s = to_srt[s].tolist()
            va_s = va_srt[s].tolist()
            sn = snaps[s]
            ridx = rd_idx[s]
            pos = order[s]
            for n in range(N):
                if to_s[n]:
                    continue
                if f_s[n] == FC_ADD:
                    insort(applied, int(va_s[n]))
                else:
                    sn.append(list(applied))
                    ridx[pos[n]] = len(sn) - 1
        is_rd = flat(fop) == FC_SRD
        va_cmp[is_rd] = rd_idx[is_rd]

    # timeout rows: info with the invoke payload, machine untouched
    to_flat = flat(TO)
    tc_cmp = np.where(to_flat, TC_INFO, tc_cmp)
    pk_cmp = np.where(to_flat, flat(pki), pk_cmp)
    va_cmp = np.where(to_flat, flat(vai), va_cmp)
    vb_cmp = np.where(to_flat, flat(vbi), vb_cmp)
    vc_cmp = np.where(to_flat, -1, vc_cmp)

    # -- assemble (R, S) row blocks; _finish restores per-seed order --
    ftr = lambda a: flat(a).T                 # (N, S) row-major blocks
    NEG1 = np.full((N, S), -1, np.int64)
    TRUE = np.ones((N, S), bool)
    blocks = {
        "time": [ftr(inv_i), ftr(cmp_i)],
        "tc": [np.zeros((N, S), np.int64), ftr(tc_cmp)],
        "fc": [ftr(fop), ftr(fop)],
        "proc": [ftr(proc), ftr(proc)],
        "key": [ftr(key_col), ftr(key_col)],
        "pk": [ftr(pki), ftr(pk_cmp)],
        "va": [ftr(vai), ftr(va_cmp)],
        "vb": [ftr(vbi), ftr(vb_cmp)],
        "vc": [NEG1, ftr(vc_cmp)],
        "act": [TRUE, TRUE],
    }
    steps = N
    if nem_blocks is not None:
        blocks["time"].append(nem_blocks["time"].T)
        blocks["tc"].append(nem_blocks["tc"].T)
        blocks["fc"].append(nem_blocks["fc"].T)
        blocks["proc"].append(np.full(nem_blocks["time"].T.shape, -1,
                                      np.int64))
        blocks["key"].append(np.full(nem_blocks["time"].T.shape, -1,
                                     np.int64))
        blocks["pk"].append(nem_blocks["pk"].T)
        blocks["va"].append(nem_blocks["va"].T)
        blocks["vb"].append(nem_blocks["vb"].T)
        blocks["vc"].append(np.full(nem_blocks["time"].T.shape, -1,
                                    np.int64))
        blocks["act"].append(nem_blocks["act"].T)
        steps += int(nem_blocks["act"].sum())
    cat = {k: np.concatenate(v, axis=0) for k, v in blocks.items()}
    histories, events = _finish(
        config, seeds, list(cat["time"]), list(cat["tc"]),
        list(cat["fc"]), list(cat["proc"]), list(cat["key"]),
        list(cat["pk"]), list(cat["va"]), list(cat["vb"]),
        list(cat["vc"]), list(cat["act"]), snaps)
    return {"histories": histories, "epoch": GEN_EPOCH_V3,
            "seeds": seeds, "events": events, "steps": steps,
            "compactions": 0}
