"""BatchHeap: an SoA event queue with a leading seed axis.

The batched generator (engine.py) advances S independent discrete-event
simulations in lockstep; its event queue is therefore S priority queues
popped together, one numpy step per drain. Layout is
structure-of-arrays with the seed axis leading — ``time``/``ord``/
``kind``/``lane`` are ``(S, capacity)`` int arrays plus a tombstone
bitmap — so every queue operation is a handful of vectorized reductions
over the seed axis instead of S Python heap manipulations.

Ordering (the generator-epoch contract, documented next to the
epoch-v1 rule in runner/sim.py):

- epoch-v1: entries order by ``(time, seq)`` — same-instant entries
  drain in push order, exactly SimLoop's heap rule.
- epoch-v2: entries order by ``(time, lane, seq)`` — same-instant
  entries drain in ascending owning-lane order, push order only as the
  final tiebreak. This is the declared rule the per-seed golden hashes
  pin.

Tombstones mirror SimLoop.Timer.cancel: ``cancel`` marks matching live
entries dead in place (they keep their slot and are skipped by every
drain); ``compact`` squeezes them out when they pile up, and is
drain-order neutral (the compaction-parity unit test pins that).
Capacities grow geometrically on demand, so callers never size queues.
"""

from __future__ import annotations

import numpy as np

#: sentinel time for "free slot / no event"; all real event times are
#: far below it, so per-seed minima of full rows stay meaningful
DONE = np.int64(2) ** 62

EPOCH_V1 = "epoch-v1"
EPOCH_V2 = "epoch-v2"
#: jitted device engine (engine_jax.py). Same ``(time, lane, seq)``
#: ordering rule as epoch-v2 — the drain order is materialized as one
#: argsort over lane-residue-unique times instead of popped
#: incrementally, identical by the unique-times argument above.
EPOCH_V3 = "epoch-v3"

#: lane id bit-position in the epoch-v2 ordinal; seq occupies the low
#: bits, so lanes must fit in the remaining headroom
_LANE_SHIFT = 40


class BatchHeap:
    """S seeds' event queues as one columnar structure.

    Every mutator takes ``(S,)`` column vectors (scalars broadcast) and
    an optional ``(S,)`` boolean mask selecting which seeds
    participate; every drain returns ``(S,)`` columns plus a validity
    mask. One entry per seed per call — the batched generator's natural
    cadence (each lockstep step pops one event per live seed and pushes
    that lane's next one).
    """

    def __init__(self, n_seeds: int, capacity: int = 8,
                 epoch: str = EPOCH_V2, auto_compact: int = 16,
                 unique_times: bool = False):
        if epoch not in (EPOCH_V1, EPOCH_V2, EPOCH_V3):
            raise ValueError(f"unknown generator epoch {epoch!r}")
        self.S = int(n_seeds)
        self.capacity = max(2, int(capacity))
        self.epoch = epoch
        #: caller guarantees no two live entries of one seed ever share
        #: a time (the engine's lane-residue encoding). The epoch
        #: ordering rule then never has to arbitrate, so pops skip the
        #: ordinal tie-break and slot-pushes skip ordinal bookkeeping —
        #: results are identical by construction, just cheaper.
        self.unique_times = bool(unique_times)
        #: tombstone count per seed that triggers an automatic compact
        #: on the next push (parity-tested; tests pin it low to force
        #: compaction traffic)
        self.auto_compact = int(auto_compact)
        self.time = np.full((self.S, self.capacity), DONE, np.int64)
        self.ordv = np.full((self.S, self.capacity), DONE, np.int64)
        self.kind = np.zeros((self.S, self.capacity), np.int64)
        self.lane = np.zeros((self.S, self.capacity), np.int64)
        self.dead = np.zeros((self.S, self.capacity), bool)
        self.live = np.zeros(self.S, np.int64)
        self.n_dead = np.zeros(self.S, np.int64)
        self.seq = np.zeros(self.S, np.int64)
        self.compactions = 0
        self._rows = np.arange(self.S)
        self._any_dead = False

    # -- internals -----------------------------------------------------------
    def _ord(self, lanes: np.ndarray) -> np.ndarray:
        if self.epoch == EPOCH_V2:
            return (lanes.astype(np.int64) << _LANE_SHIFT) | self.seq
        return self.seq.copy()

    def _eff_time(self) -> np.ndarray:
        """Per-slot times with tombstones masked out of every drain."""
        if not self._any_dead:
            return self.time
        return np.where(self.dead, DONE, self.time)

    def _grow(self) -> None:
        cap2 = self.capacity * 2
        for name in ("time", "ordv", "kind", "lane", "dead"):
            old = getattr(self, name)
            fill = DONE if name in ("time", "ordv") else 0
            new = np.full((self.S, cap2), fill, old.dtype)
            new[:, :self.capacity] = old
            setattr(self, name, new)
        self.capacity = cap2

    # -- mutators ------------------------------------------------------------
    def push(self, times, lanes, kinds, mask=None) -> None:
        """Insert one entry per selected seed."""
        times = np.broadcast_to(np.asarray(times, np.int64), (self.S,))
        lanes = np.broadcast_to(np.asarray(lanes, np.int64), (self.S,))
        kinds = np.broadcast_to(np.asarray(kinds, np.int64), (self.S,))
        if mask is None:
            mask = np.ones(self.S, bool)
        if not mask.any():
            return
        if int(self.n_dead.max()) >= self.auto_compact:
            self.compact()
        free = (self.time == DONE) & ~self.dead
        if ((free.sum(axis=1) == 0) & mask).any():
            if int(self.n_dead.max()) > 0:
                self.compact()
                free = (self.time == DONE) & ~self.dead
            if ((free.sum(axis=1) == 0) & mask).any():
                self._grow()
                free = (self.time == DONE) & ~self.dead
        slot = free.argmax(axis=1)
        ordv = self._ord(lanes)
        rows = self._rows[mask]
        sl = slot[mask]
        self.time[rows, sl] = times[mask]
        self.ordv[rows, sl] = ordv[mask]
        self.kind[rows, sl] = kinds[mask]
        self.lane[rows, sl] = lanes[mask]
        self.live += mask
        self.seq += mask

    def push_slots(self, times, lanes, kinds, mask) -> None:
        """Slot-addressed fast-path push: the entry for lane ``l`` goes
        to slot ``l`` directly. Sound ONLY under the lockstep
        generator's cadence — each lane owns at most one live entry at
        a time, so slot=lane is a free-slot assignment by construction
        (capacity must exceed the highest lane id, and the lane's slot
        must not hold a tombstone). Ordering semantics are identical to
        :meth:`push`: slots never influence drain order (pop resolves
        ties by the epoch ordinal alone), and the per-seed ``seq``
        counter advances exactly as a general push would, so histories
        are bit-identical across the two paths. Under ``unique_times``
        the ordinal is provably never consulted and its bookkeeping is
        skipped. All four operands must be ``(S,)`` arrays."""
        rows = self._rows[mask]
        sl = lanes[mask]
        self.time[rows, sl] = times[mask]
        self.kind[rows, sl] = kinds[mask]
        self.lane[rows, sl] = lanes[mask]
        self.live += mask
        if not self.unique_times:
            self.ordv[rows, sl] = self._ord(lanes)[mask]
            self.seq += mask

    def cancel(self, lanes, mask=None, kind=None) -> None:
        """Tombstone every live entry owned by the given lane (and
        kind, when given), per selected seed — SimLoop's Timer.cancel
        analog: the entry keeps its slot, drains skip it, compaction
        reclaims it."""
        lanes = np.broadcast_to(np.asarray(lanes, np.int64), (self.S,))
        m = (self.lane == lanes[:, None]) & (self.time != DONE) \
            & ~self.dead
        if kind is not None:
            m &= self.kind == kind
        if mask is not None:
            m &= mask[:, None]
        n = m.sum(axis=1)
        self.dead |= m
        self.n_dead += n
        self.live -= n
        self._any_dead = self._any_dead or bool(n.any())

    def compact(self) -> None:
        """Squeeze tombstones out, preserving live-entry slot order
        (stable), so drain order is unchanged by construction."""
        if not self.n_dead.any():
            return
        livem = (self.time != DONE) & ~self.dead
        order = np.argsort(~livem, axis=1, kind="stable")
        t = np.where(livem, self.time, DONE)
        o = np.where(livem, self.ordv, DONE)
        self.time = np.take_along_axis(t, order, axis=1)
        self.ordv = np.take_along_axis(o, order, axis=1)
        self.kind = np.take_along_axis(self.kind, order, axis=1)
        self.lane = np.take_along_axis(self.lane, order, axis=1)
        self.dead = np.zeros((self.S, self.capacity), bool)
        self.n_dead[:] = 0
        self._any_dead = False
        self.compactions += 1

    # -- drains --------------------------------------------------------------
    def peek_time(self) -> np.ndarray:
        """Per-seed minimum live event time (DONE where empty)."""
        return self._eff_time().min(axis=1)

    def pop_min(self):
        """Pop the per-seed minimum entry under the epoch's ordering.

        Returns ``(time, kind, lane, has)`` — ``(S,)`` columns plus the
        validity mask (False rows carry garbage)."""
        eff = self._eff_time()
        rows = self._rows
        if self.unique_times:
            # no ties by caller contract: argmin of time IS the epoch
            # order; a DONE re-write on empty rows is a no-op
            slot = eff.argmin(axis=1)
            tmin = eff[rows, slot]
            has = tmin != DONE
            kind = self.kind[rows, slot]
            lane = self.lane[rows, slot]
            self.time[rows, slot] = DONE
            self.live -= has
            return tmin, kind, lane, has
        tmin = eff.min(axis=1)
        has = tmin < DONE
        o = np.where(eff == tmin[:, None], self.ordv, DONE)
        slot = o.argmin(axis=1)
        kind = self.kind[rows, slot]
        lane = self.lane[rows, slot]
        r = rows[has]
        s = slot[has]
        self.time[r, s] = DONE
        self.ordv[r, s] = DONE
        self.live -= has
        return tmin, kind, lane, has

    def pop_same_instant(self):
        """Batched same-instant drain: pop EVERY entry at the per-seed
        minimum time, ordered along axis 1 by the epoch's rule.

        Returns ``(time, kinds, lanes, count)`` with kinds/lanes shaped
        ``(S, m)`` (m = widest batch; rows padded past ``count``)."""
        eff = self._eff_time()
        tmin = eff.min(axis=1)
        due = (eff == tmin[:, None]) & (tmin[:, None] < DONE)
        count = due.sum(axis=1)
        m = int(count.max()) if len(count) else 0
        o = np.where(due, self.ordv, DONE)
        order = np.argsort(o, axis=1, kind="stable")
        kinds = np.take_along_axis(self.kind, order, axis=1)[:, :m]
        lanes = np.take_along_axis(self.lane, order, axis=1)[:, :m]
        self.time[due] = DONE
        self.ordv[due] = DONE
        self.live -= count
        return tmin, kinds, lanes, count

    # -- introspection -------------------------------------------------------
    def size(self) -> np.ndarray:
        return self.live.copy()

    def __repr__(self) -> str:
        return (f"<BatchHeap {self.S} seeds cap={self.capacity} "
                f"epoch={self.epoch} live={self.live.tolist()}>")
