"""simbatch: S seeds' discrete-event simulations in lockstep columnar
numpy steps, histories born as OpColumns (generator epoch-v2).

Public surface:

- :func:`generate` / :func:`generate_for_opts` — run a seed batch,
  get back per-seed Histories (column-backed, zero conversion into the
  checker pipeline) plus genbatch stats.
- :class:`BatchConfig` — the stable opts→sizing mapping golden hashes
  key on.
- :class:`BatchHeap` — the SoA event queue (tombstone cancels, batched
  same-instant drains, drain-order-neutral compaction).
- :func:`history_sha` — the golden-hash function (sha256 of the
  canonical jsonl serialization), test/bench use only: it materializes
  op dicts, which the hot paths never do.

The determinism contract (what epoch-v2 means, and why verdicts — not
histories — must match epoch-v1) is documented in engine.py and in the
epoch ledger in runner/sim.py.
"""

from __future__ import annotations

import hashlib

from .engine import (  # noqa: F401
    GEN_EPOCH_V1,
    GEN_EPOCH_V2,
    STRIDE,
    SUPPORTED_WORKLOADS,
    BatchConfig,
    default_schedule,
    generate,
    generate_for_opts,
    schedule_span,
    supports,
)
from .engine_jax import (  # noqa: F401
    GEN_EPOCH_V3,
    default_schedule_jax,
    generate_jax,
)
from .heap import DONE, BatchHeap  # noqa: F401


def history_sha(history) -> str:
    """Golden hash of a history: sha256 over the canonical jsonl
    serialization (tests/bench only — materializes dicts)."""
    return hashlib.sha256(history.to_jsonl().encode()).hexdigest()
