"""Byte-level WAL and snapshot model for corruption faults.

The reference's corruption nemesis bitflips or truncates real etcd WAL/snap
files on disk (``nemesis.clj:145-198``), and etcd reacts by panicking on
CRC mismatch at replay. Our simulated nodes keep a ``RecordFile`` per
"file": records live as Python objects until a corruption fault touches
the file, at which point the framed per-record-CRC byte buffer is
materialized and becomes authoritative, so the same fault surface exists:
flipping a bit corrupts exactly one record's CRC; truncating drops tail
records; replay stops at the first bad record (etcd WAL semantics) or —
if a *committed* record is damaged — the node refuses to start with a
panic in its log (cf. the log-file-pattern crash checker,
etcd.clj:134-140). Lazy materialization matters because value-carrying
records made per-append pickling O(history²) on append-heavy workloads;
the reference pays that encoding cost for real, to real disks, while the
sim only needs the bytes when a fault inspects them.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import pickle
import struct
import zlib
from typing import Any, Optional


MAGIC = b"WALR"


def encode_records(items: list[Any]) -> bytes:
    """Encode items as length+crc framed records."""
    out = bytearray()
    for item in items:
        payload = pickle.dumps(item, protocol=4)
        crc = zlib.crc32(payload)
        out += MAGIC + struct.pack("<II", len(payload), crc) + payload
    return bytes(out)


def record_bytes(item: Any) -> bytes:
    """One framed record (MAGIC + length + crc + pickled payload)."""
    payload = pickle.dumps(item, protocol=4)
    crc = zlib.crc32(payload)
    return MAGIC + struct.pack("<II", len(payload), crc) + payload


def decode_records(buf: bytes) -> tuple[list[Any], Optional[str]]:
    """Decode records until the first damaged one.

    Returns (items, error) where error is None for a clean read,
    "crc-mismatch" for a corrupted record, "torn-record" for a truncated
    tail (etcd tolerates a torn final record: it was mid-write at crash).
    """
    items: list[Any] = []
    at = 0
    n = len(buf)
    while at < n:
        if at + 12 > n:
            return items, "torn-record"
        if buf[at:at + 4] != MAGIC:
            return items, "crc-mismatch"
        ln, crc = struct.unpack("<II", buf[at + 4:at + 12])
        if at + 12 + ln > n:
            return items, "torn-record"
        payload = buf[at + 12:at + 12 + ln]
        if zlib.crc32(payload) != crc:
            return items, "crc-mismatch"
        try:
            items.append(pickle.loads(payload))
        except Exception:
            return items, "crc-mismatch"
        at += 12 + ln
    return items, None


#: walk one appended record's payload in every EST_SAMPLE for the
#: db-size estimate; the rest extrapolate from the running mean
EST_SAMPLE = 16


def _est_size(x: Any, _depth: int = 0) -> int:
    """Cheap framed-record size estimate for OBJ-mode files (db-size
    stat only). Big homogeneous containers are sampled, not walked, so
    the estimate is O(1) per value instead of O(len) — an append-heavy
    run must not pay per-element costs for an informational stat."""
    if isinstance(x, (int, float, bool)) or x is None:
        return 9
    if isinstance(x, (str, bytes)):
        return 10 + len(x)
    if isinstance(x, (list, tuple, set, frozenset)):
        n = len(x)
        if _depth > 4 or n == 0:
            return 10 + 9 * n
        xs = list(x) if isinstance(x, (set, frozenset)) else x
        if n > 64:
            per = sum(_est_size(v, _depth + 1) for v in xs[:16]) / 16.0
            return 10 + int(per * n)
        return 10 + sum(_est_size(v, _depth + 1) for v in xs)
    if isinstance(x, dict):
        n = len(x)
        if _depth > 4 or n == 0:
            return 16 + 18 * n
        if n > 32:
            per = sum(_est_size(k, _depth + 1) + _est_size(v, _depth + 1)
                      for k, v in itertools.islice(x.items(), 16)) / 16.0
            return 16 + int(per * n)
        return 16 + sum(_est_size(k, _depth + 1) + _est_size(v, _depth + 1)
                        for k, v in x.items())
    if hasattr(x, "est_size"):
        return x.est_size()     # e.g. the Store inside a snapshot record
    if dataclasses.is_dataclass(x) and not isinstance(x, type):
        # e.g. the Txn payload of a WAL record — its compare/success/
        # failure tuples carry the (possibly large) values
        return 32 + sum(_est_size(getattr(x, f.name), _depth + 1)
                        for f in dataclasses.fields(x))
    return 48


class RecordFile:
    """A simulated on-disk record file with lazy byte materialization.

    Two modes:

    - **OBJ mode** (default): records live as Python objects; the
      durable view is a second list. Appends, fsyncs, replay, and
      unfsynced-loss are all object operations — no pickling. This is
      the fast path for every run that never corrupts the file, and it
      removes the O(history²) byte-encoding cost of value-carrying
      records (the reference pays that cost for real, to real disks;
      the sim only needs bytes when a fault inspects them).
    - **BYTES mode**: entered when a corruption fault touches the raw
      bytes (``corrupt``). The framed CRC buffer from ``encode_records``
      becomes authoritative for both views and replay decodes it, so
      the reference's fault surface (nemesis.clj:145-198 — bitflips
      break one record's CRC, truncation drops tail records) is
      byte-exact. ``set_records`` / ``clear`` return to OBJ mode (etcd
      rewrites the file wholesale on recovery/snapshot).
    """

    def __init__(self) -> None:
        # each view is independently OBJ (items list) or BYTES (buffer
        # not None); an unsynced rewrite can leave the durable view as
        # damaged bytes while the current view is fresh objects — the
        # damage must survive until an fsynced rewrite replaces it
        self._items: list = []
        self._durable: list = []
        self._bytes: Optional[bytearray] = None
        self._durable_bytes: Optional[bytearray] = None
        # OBJ-mode size estimate (current view): a sampled running
        # average — walking every appended payload charged the hot
        # append path ~16% of a whole run's generation for a stat
        # (db-size) that is read rarely. One record in EST_SAMPLE is
        # walked; the rest extrapolate from the running per-record mean
        self._est_sampled = 0.0   # bytes across sampled records
        self._est_samples = 0
        self._est_count = 0       # records since last wholesale rewrite

    # -- mode helpers --------------------------------------------------------

    @property
    def byte_mode(self) -> bool:
        return self._bytes is not None

    # -- writes --------------------------------------------------------------

    def append(self, item: Any, sync: bool) -> None:
        if self._bytes is not None:
            self._bytes += record_bytes(item)
        else:
            self._items.append(item)
            self._est_count += 1
            if self._est_count % EST_SAMPLE == 1 or self._est_samples < 4:
                self._est_sampled += 22 + _est_size(item)
                self._est_samples += 1
        if sync:
            if self._durable_bytes is not None:
                self._durable_bytes += record_bytes(item)
            else:
                self._durable.append(item)

    def set_records(self, items: list, sync: bool) -> None:
        """Wholesale rewrite (recovery re-encode, snapshot save, conflict
        truncation): the current view returns to OBJ mode. Unsynced
        rewrites leave the durable view untouched — including damaged
        bytes, which must keep failing CRC at a later rollback+replay."""
        self._bytes = None
        self._items = list(items)
        self._reset_est()
        if sync:
            self._durable_bytes = None
            self._durable = list(items)

    def clear(self) -> None:
        self.set_records([], sync=True)

    def fsync(self) -> None:
        if self._bytes is not None:
            self._durable_bytes = bytearray(self._bytes)
            self._durable = []
        else:
            self._durable_bytes = None
            self._durable = list(self._items)

    def lose_unfsynced(self) -> None:
        """Crash without fsync: the current view rolls back to durable."""
        if self._durable_bytes is not None:
            self._bytes = bytearray(self._durable_bytes)
            self._items = []
        else:
            self._bytes = None
            self._items = list(self._durable)
            self._reset_est()

    def corrupt(self, rng, mode: str = "bitflip",
                probability: float = 1e-4, truncate_bytes: int = 1024) -> None:
        """Damage the file's bytes; both views end up with the damaged
        buffer (the fault hits the one real file on disk)."""
        if self._bytes is None:
            self._bytes = bytearray(encode_records(self._items))
            self._items = []
        buf = bytes(self._bytes)
        if mode == "bitflip":
            buf = bitflip(buf, rng, probability)
        else:
            buf = truncate(buf, rng, truncate_bytes)
        self._bytes = bytearray(buf)
        self._durable_bytes = bytearray(buf)
        self._durable = []

    # -- reads ---------------------------------------------------------------

    def read(self) -> tuple[list, Optional[str]]:
        """Replay the current view: (records, error)."""
        if self._bytes is not None:
            return decode_records(bytes(self._bytes))
        return list(self._items), None

    def _reset_est(self) -> None:
        """Re-seed the sampled estimate after a wholesale rewrite:
        walk up to EST_SAMPLE samples of the new contents,
        extrapolate."""
        items = self._items
        n = len(items)
        self._est_count = n
        if n <= EST_SAMPLE:
            self._est_sampled = float(
                sum(22 + _est_size(i) for i in items))
            self._est_samples = n
        else:
            step = n // EST_SAMPLE
            sample = items[::step][:EST_SAMPLE]
            self._est_sampled = float(
                sum(22 + _est_size(i) for i in sample))
            self._est_samples = len(sample)

    @property
    def size(self) -> int:
        # Unserialized files report a SAMPLED estimate (EST_SAMPLE items
        # extrapolated to the full count), not an exact byte size. Fine
        # for perf plots and relative comparisons; do NOT gate threshold
        # logic (quota, corruption windows) on it — serialize first if
        # an exact size matters.
        if self._bytes is not None:
            return len(self._bytes)
        if not self._est_samples:
            return 0
        return int(self._est_sampled / self._est_samples
                   * self._est_count)


def bitflip(buf: bytes, rng, probability: float) -> bytes:
    """Flip each bit independently with the given probability
    (nemesis.clj:183 uses probabilities 1e-3..1e-5)."""
    if not buf or probability <= 0:
        return buf
    probability = min(probability, 0.999999)
    out = bytearray(buf)
    nbits = len(out) * 8
    # Binomial sample via repeated geometric skips (cheap, deterministic).
    pos = -1
    while True:
        r = rng.random()
        skip = int(math.log(max(r, 1e-12)) / math.log(1 - probability)) + 1
        pos += skip
        if pos >= nbits:
            break
        out[pos // 8] ^= 1 << (pos % 8)
    return bytes(out)


def truncate(buf: bytes, rng, max_bytes: int = 1024) -> bytes:
    """Drop up to max_bytes from the tail (nemesis.clj:182)."""
    if not buf:
        return buf
    drop = rng.randint(1, max_bytes)
    return buf[:max(0, len(buf) - drop)]
