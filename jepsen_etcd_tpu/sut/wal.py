"""Byte-level WAL and snapshot model for corruption faults.

The reference's corruption nemesis bitflips or truncates real etcd WAL/snap
files on disk (``nemesis.clj:145-198``), and etcd reacts by panicking on
CRC mismatch at replay. Our simulated nodes keep an actual byte buffer per
"file" with per-record CRCs so the same fault surface exists: flipping a
bit corrupts exactly one record's CRC; truncating drops tail records;
replay stops at the first bad record (etcd WAL semantics) or — if a
*committed* record is damaged — the node refuses to start with a panic in
its log (cf. the log-file-pattern crash checker, etcd.clj:134-140).
"""

from __future__ import annotations

import math
import pickle
import struct
import zlib
from typing import Any, Optional


MAGIC = b"WALR"


def encode_records(items: list[Any]) -> bytes:
    """Encode items as length+crc framed records."""
    out = bytearray()
    for item in items:
        payload = pickle.dumps(item, protocol=4)
        crc = zlib.crc32(payload)
        out += MAGIC + struct.pack("<II", len(payload), crc) + payload
    return bytes(out)


def record_bytes(item: Any) -> bytes:
    """One framed record (MAGIC + length + crc + pickled payload)."""
    payload = pickle.dumps(item, protocol=4)
    crc = zlib.crc32(payload)
    return MAGIC + struct.pack("<II", len(payload), crc) + payload


def decode_records(buf: bytes) -> tuple[list[Any], Optional[str]]:
    """Decode records until the first damaged one.

    Returns (items, error) where error is None for a clean read,
    "crc-mismatch" for a corrupted record, "torn-record" for a truncated
    tail (etcd tolerates a torn final record: it was mid-write at crash).
    """
    items: list[Any] = []
    at = 0
    n = len(buf)
    while at < n:
        if at + 12 > n:
            return items, "torn-record"
        if buf[at:at + 4] != MAGIC:
            return items, "crc-mismatch"
        ln, crc = struct.unpack("<II", buf[at + 4:at + 12])
        if at + 12 + ln > n:
            return items, "torn-record"
        payload = buf[at + 12:at + 12 + ln]
        if zlib.crc32(payload) != crc:
            return items, "crc-mismatch"
        try:
            items.append(pickle.loads(payload))
        except Exception:
            return items, "crc-mismatch"
        at += 12 + ln
    return items, None


def bitflip(buf: bytes, rng, probability: float) -> bytes:
    """Flip each bit independently with the given probability
    (nemesis.clj:183 uses probabilities 1e-3..1e-5)."""
    if not buf or probability <= 0:
        return buf
    probability = min(probability, 0.999999)
    out = bytearray(buf)
    nbits = len(out) * 8
    # Binomial sample via repeated geometric skips (cheap, deterministic).
    pos = -1
    while True:
        r = rng.random()
        skip = int(math.log(max(r, 1e-12)) / math.log(1 - probability)) + 1
        pos += skip
        if pos >= nbits:
            break
        out[pos // 8] ^= 1 << (pos % 8)
    return bytes(out)


def truncate(buf: bytes, rng, max_bytes: int = 1024) -> bytes:
    """Drop up to max_bytes from the tail (nemesis.clj:182)."""
    if not buf:
        return buf
    drop = rng.randint(1, max_bytes)
    return buf[:max(0, len(buf) - drop)]
