"""The etcd MVCC state machine: a pure, deterministic store.

Models the semantics the reference exercises through jetcd
(``client.clj:405-527`` KV/txn surface, ``append.clj:85-97`` guard
semantics, ``register.clj:31-39`` version bookkeeping, watch event shape at
``watch.clj:156-160``):

- a global ``revision`` counter, bumped once per mutating applied txn;
- per key: ``value``, ``version`` (puts since creation; delete resets),
  ``create_revision``, ``mod_revision``, optional ``lease`` id;
- If/Then/Else transactions whose comparisons read version / value /
  mod_revision / create_revision with ``=``, ``<``, ``>``;
  *absent keys compare with version=0, mod_revision=0, create_revision=0*
  (this is what makes the reference's absent-key guard
  ``(t/< k (t/mod-revision read-revision))`` work, append.clj:93-96);
- tombstoned deletes, compaction (reads/watches below the compact
  revision raise "compacted");
- an event log (per-revision) from which watch streams are served.

The store is the *applied* state of one replica; replication order is the
cluster's job (cluster.py). Pure apply => every replica that applies the
same entries in the same order has an identical store (checked by the
corruption detector).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Optional

from .errors import SimError


# -- txn AST (server side) ---------------------------------------------------

def get_op(key: str) -> tuple:
    return ("get", key)


def put_op(key: str, value: Any, lease: int = 0) -> tuple:
    return ("put", key, value, lease)


def del_op(key: str) -> tuple:
    return ("delete", key)


def range_op(prefix: str) -> tuple:
    """Prefix scan (used by the lock service and debugging)."""
    return ("range", prefix)


def cmp(op: str, key: str, target: str, operand: Any) -> tuple:
    """Comparison: op in {=, <, >}, target in
    {version, value, mod_revision, create_revision}."""
    if op not in ("=", "<", ">"):
        raise ValueError(f"bad cmp op {op!r}")
    if target not in ("version", "value", "mod_revision", "create_revision"):
        raise ValueError(f"bad cmp target {target!r}")
    return (op, key, target, operand)


@dataclass(frozen=True)
class Txn:
    """If(cmps) Then(then_ops) Else(else_ops); plain ops are Txns with no
    compares (executed as the then branch)."""

    cmps: tuple = ()
    then_ops: tuple = ()
    else_ops: tuple = ()


@dataclass
class KeyState:
    value: Any
    version: int
    create_revision: int
    mod_revision: int
    lease: int = 0

    def as_kv(self, key: str) -> dict:
        return {
            "key": key,
            "value": self.value,
            "version": self.version,
            "create-revision": self.create_revision,
            "mod-revision": self.mod_revision,
            "lease": self.lease,
        }


@dataclass
class Event:
    """A watch event (watch.clj:156-160 reads :mod-revision of each kv)."""

    type: str  # "put" | "delete"
    key: str
    kv: Optional[dict]       # state after (None for delete)
    prev_kv: Optional[dict]  # state before (None for create)
    revision: int


class Store:
    """One replica's applied MVCC state."""

    def __init__(self):
        self.revision = 1          # etcd starts at revision 1
        self.compact_revision = 0
        self.kvs: dict[str, KeyState] = {}
        self.events: list[tuple[int, list[Event]]] = []  # (rev, events)
        # clones share the events list copy-on-write (snapshots clone
        # every snapshot_count entries; eagerly copying the whole event
        # history each time is O(history) per snapshot)
        self._events_shared = False
        # lease id -> set of keys currently attached (rebuilt with state)
        self.lease_keys: dict[int, set] = {}

    # -- reads --------------------------------------------------------------

    def get(self, key: str) -> Optional[dict]:
        ks = self.kvs.get(key)
        return ks.as_kv(key) if ks is not None else None

    def range_prefix(self, prefix: str) -> list[dict]:
        out = [ks.as_kv(k) for k, ks in self.kvs.items()
               if k.startswith(prefix)]
        out.sort(key=lambda kv: kv["key"])
        return out

    def range_interval(self, start: str,
                       end: Optional[str] = None) -> list[dict]:
        """etcd Range semantics: end None -> the single key `start`;
        end "\\0" -> every key >= start; else the half-open interval
        [start, end)."""
        if end is None:
            kv = self.get(start)
            return [kv] if kv else []
        out = [ks.as_kv(k) for k, ks in self.kvs.items()
               if k >= start and (end == "\x00" or k < end)]
        out.sort(key=lambda kv: kv["key"])
        return out

    # -- txn evaluation -----------------------------------------------------

    def _cmp_value(self, key: str, target: str) -> Any:
        ks = self.kvs.get(key)
        if ks is None:
            # etcd compares against zero-valued KeyValue for absent keys.
            return None if target == "value" else 0
        return getattr(ks, {"version": "version",
                            "value": "value",
                            "mod_revision": "mod_revision",
                            "create_revision": "create_revision"}[target])

    def check(self, c: tuple) -> bool:
        op, key, target, operand = c
        actual = self._cmp_value(key, target)
        if op == "=":
            return actual == operand
        if actual is None or operand is None:
            return False  # < and > are undefined on nil values
        if op == "<":
            return actual < operand
        return actual > operand

    def apply_txn(self, txn: Txn) -> dict:
        """Apply a transaction; returns
        {succeeded, results, revision, events, mutated}.

        Mutating txns bump the revision by exactly one; all puts/deletes in
        the txn share the new mod_revision (etcd semantics). The caller
        (replica apply loop) is responsible for ordering.
        """
        succeeded = all(self.check(c) for c in txn.cmps)
        ops = txn.then_ops if succeeded else txn.else_ops
        mutates = any(o[0] in ("put", "delete") for o in ops)
        new_rev = self.revision + 1 if mutates else self.revision
        results = []
        events: list[Event] = []
        for o in ops:
            kind = o[0]
            if kind == "get":
                results.append(("get", self.get(o[1])))
            elif kind == "range":
                results.append(("range", self.range_prefix(o[1])))
            elif kind == "put":
                _, key, value, lease = o
                prev = self.kvs.get(key)
                prev_kv = prev.as_kv(key) if prev else None
                # values are immutable by convention once written (every
                # client/workload builds fresh containers per put); a
                # shallow copy guards against top-level reuse without
                # the O(elements) deepcopy that made big-list workloads
                # (set: one ever-growing list) quadratic
                if prev is None:
                    ks = KeyState(value=copy.copy(value), version=1,
                                  create_revision=new_rev,
                                  mod_revision=new_rev, lease=lease)
                else:
                    if prev.lease and prev.lease != lease:
                        self.lease_keys.get(prev.lease, set()).discard(key)
                    ks = KeyState(value=copy.copy(value),
                                  version=prev.version + 1,
                                  create_revision=prev.create_revision,
                                  mod_revision=new_rev, lease=lease)
                self.kvs[key] = ks
                if lease:
                    self.lease_keys.setdefault(lease, set()).add(key)
                results.append(("put", prev_kv))
                events.append(Event("put", key, ks.as_kv(key), prev_kv,
                                    new_rev))
            elif kind == "delete":
                key = o[1]
                prev = self.kvs.pop(key, None)
                prev_kv = prev.as_kv(key) if prev else None
                if prev is not None and prev.lease:
                    self.lease_keys.get(prev.lease, set()).discard(key)
                results.append(("delete", 1 if prev else 0))
                if prev is not None:
                    events.append(Event("delete", key, None, prev_kv,
                                        new_rev))
            else:
                raise ValueError(f"unknown txn op {o!r}")
        if mutates:
            self.revision = new_rev
            if events:
                if self._events_shared:
                    # break COW sharing before the in-place append;
                    # entries are immutable once committed, so a
                    # shallow copy suffices
                    self.events = list(self.events)
                    self._events_shared = False
                self.events.append((new_rev, events))
        return {"succeeded": succeeded, "results": results,
                "revision": self.revision, "events": events,
                "mutated": mutates}

    # -- compaction ---------------------------------------------------------

    def compact(self, rev: int) -> None:
        if rev > self.revision:
            raise SimError("compacted",
                           f"compact revision {rev} > current {self.revision}",
                           definite=True)
        self.compact_revision = max(self.compact_revision, rev)
        # rebuilds (rather than mutates) the list, so sharing clones
        # keep their view; this store's copy is now unshared
        self.events = [(r, evs) for r, evs in self.events
                       if r > self.compact_revision]
        self._events_shared = False

    def events_since(self, rev: int) -> list[Event]:
        """Events with revision >= rev (for watch catch-up).

        Raises compacted if rev is at/below the compact horizon.
        """
        if rev <= self.compact_revision:
            err = SimError("compacted",
                           f"watch from {rev} <= compacted "
                           f"{self.compact_revision}")
            # like etcd's WatchResponse.compact_revision: tells the
            # watcher where it may restart (watch.clj:243-267 retry)
            err.compact_revision = self.compact_revision
            raise err
        out: list[Event] = []
        for r, evs in self.events:
            if r >= rev:
                out.extend(evs)
        return out

    # -- snapshot / state hash ----------------------------------------------

    def state_fingerprint(self) -> int:
        """Deterministic hash of current kv state, for corruption checks
        (the analog of etcd's --experimental-corrupt-check-time).
        Uses crc32 over a canonical encoding — Python's salted hash()
        would break cross-run reproducibility."""
        import zlib
        parts = [f"rev={self.revision}"]
        for k in sorted(self.kvs):
            ks = self.kvs[k]
            parts.append(f"{k}={ks.value!r}:{ks.version}:"
                         f"{ks.create_revision}:{ks.mod_revision}:{ks.lease}")
        return zlib.crc32("\n".join(parts).encode())

    def est_size(self) -> int:
        """Rough byte-size estimate for the db-size stat (picked up by
        wal._est_size when this store sits inside an OBJ-mode snapshot
        record): tracks kv payload and retained-event volume without
        pickling the state."""
        from .wal import _est_size
        import itertools
        sz = 64
        n = len(self.kvs)
        if n:
            sample = itertools.islice(self.kvs.items(), 64)
            per = sum(32 + len(k) + _est_size(ks.value)
                      for k, ks in sample) / min(n, 64)
            sz += int(per * n)
        sz += 24 * len(self.events)
        return sz

    def clone(self) -> "Store":
        new = Store.__new__(Store)
        new.revision = self.revision
        new.compact_revision = self.compact_revision
        # stored values are never mutated in place (puts replace the
        # KeyState wholesale), so clones can share them
        new.kvs = {k: KeyState(v.value, v.version,
                               v.create_revision, v.mod_revision, v.lease)
                   for k, v in self.kvs.items()}
        # events share copy-on-write: (rev, events) entries are
        # immutable once committed, and the first in-place append on
        # either side breaks the sharing
        new.events = self.events
        new._events_shared = True
        self._events_shared = True
        new.lease_keys = {l: set(ks) for l, ks in self.lease_keys.items()}
        return new
