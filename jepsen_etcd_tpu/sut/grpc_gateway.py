"""Native-gRPC etcd v3 server over the simulated MVCC store.

The gRPC sibling of ``http_gateway.py``, sharing its ``GatewayState``
(one Store, total order via a lock). Two jobs:

- the hermetic test double for the native-gRPC client adapter
  (client/etcd_grpc.py): the adapter speaks the same frames to this
  server as to a live etcd — etcdserverpb/v3lockpb method paths,
  proto messages with etcd's field numbers, streaming watch with
  compaction-cancel framing — so the reference's actual wire protocol
  (jetcd's, client.clj:14-68) is exercised end-to-end without an etcd
  binary;
- a live etcd-wire gRPC endpoint backed by the simulated store
  (``python -m jepsen_etcd_tpu gateway --grpc``): real etcd gRPC
  tooling can talk to the simulated store.

Handlers are registered generically (grpc.method_handlers_generic_
handler) against explicit method paths, so no grpc_tools service
codegen is needed.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Iterator

from .errors import SimError
from .store import Txn
from .http_gateway import GatewayState, member_id_for_peer_urls
from ..client.proto import etcd_rpc_pb2 as pb

_CMP_OP = {pb.Compare.EQUAL: "=", pb.Compare.LESS: "<",
           pb.Compare.GREATER: ">"}
_CMP_TARGET = {pb.Compare.VALUE: "value", pb.Compare.VERSION: "version",
               pb.Compare.MOD: "mod_revision",
               pb.Compare.CREATE: "create_revision"}


def _unval(b: bytes):
    try:
        return json.loads(b)
    except ValueError:
        return b.decode("utf-8", "replace")


def _kv_wire(kv: dict) -> pb.KeyValue:
    return pb.KeyValue(
        key=kv["key"].encode("utf-8"),
        value=json.dumps(kv["value"]).encode("utf-8"),
        version=int(kv["version"]),
        create_revision=int(kv["create-revision"]),
        mod_revision=int(kv["mod-revision"]),
        lease=int(kv.get("lease", 0)))


class _Services:
    """All service handlers over one shared GatewayState."""

    def __init__(self, state: GatewayState):
        self.st = state

    # ---- KV ----------------------------------------------------------------

    def range(self, req: pb.RangeRequest, ctx) -> pb.RangeResponse:
        key = req.key.decode("utf-8")
        range_end = req.range_end.decode("utf-8") if req.range_end \
            else None
        with self.st.lock:
            kvs = self.st.store.range_interval(key, range_end)
            rev = self.st.store.revision
        more = bool(req.limit) and len(kvs) > req.limit
        count = len(kvs)
        if req.limit:
            kvs = kvs[:req.limit]
        return pb.RangeResponse(
            header=pb.ResponseHeader(revision=rev),
            kvs=[_kv_wire(kv) for kv in kvs], more=more, count=count)

    def txn(self, req: pb.TxnRequest, ctx) -> pb.TxnResponse:
        cmps = []
        for c in req.compare:
            target = _CMP_TARGET[c.target]
            if target == "value":
                operand = _unval(c.value)
            elif target == "version":
                operand = int(c.version)
            elif target == "mod_revision":
                operand = int(c.mod_revision)
            else:
                operand = int(c.create_revision)
            cmps.append((_CMP_OP[c.result], c.key.decode("utf-8"),
                         target, operand))

        def branch(ops):
            out = []
            for o in ops:
                which = o.WhichOneof("request")
                if which == "request_range":
                    out.append(("get",
                                o.request_range.key.decode("utf-8")))
                elif which == "request_put":
                    p = o.request_put
                    out.append(("put", p.key.decode("utf-8"),
                                _unval(p.value), int(p.lease)))
                elif which == "request_delete_range":
                    out.append(("delete", o.request_delete_range.key
                                .decode("utf-8")))
            return out

        txn = Txn(tuple(cmps), tuple(branch(req.success)),
                  tuple(branch(req.failure)))
        with self.st.lock:
            raw = self.st.store.apply_txn(txn)
        resp = pb.TxnResponse(
            header=pb.ResponseHeader(revision=raw["revision"]),
            succeeded=raw["succeeded"])
        for r in raw["results"]:
            ro = resp.responses.add()
            if r[0] == "get":
                if r[1]:
                    ro.response_range.kvs.append(_kv_wire(r[1]))
                    ro.response_range.count = 1
                else:
                    ro.response_range.count = 0
            elif r[0] == "put":
                if r[1]:
                    ro.response_put.prev_kv.CopyFrom(_kv_wire(r[1]))
                else:
                    ro.response_put.SetInParent()
            else:
                ro.response_delete_range.deleted = int(r[1])
        return resp

    def compact(self, req: pb.CompactionRequest,
                ctx) -> pb.CompactionResponse:
        import grpc
        with self.st.lock:
            if req.revision <= self.st.store.compact_revision:
                ctx.abort(grpc.StatusCode.OUT_OF_RANGE,
                          "etcdserver: mvcc: required revision has "
                          "been compacted")
            self.st.store.compact(int(req.revision))
            return pb.CompactionResponse(header=pb.ResponseHeader(
                revision=self.st.store.revision))

    # ---- lease -------------------------------------------------------------

    def lease_grant(self, req: pb.LeaseGrantRequest,
                    ctx) -> pb.LeaseGrantResponse:
        with self.st.lock:
            self.st.next_lease += 1
            lid = self.st.next_lease
            self.st.leases[lid] = int(req.TTL) or 1
        return pb.LeaseGrantResponse(ID=lid, TTL=self.st.leases[lid])

    def lease_revoke(self, req: pb.LeaseRevokeRequest,
                     ctx) -> pb.LeaseRevokeResponse:
        import grpc
        lid = int(req.ID)
        with self.st.lock:
            if lid not in self.st.leases:
                ctx.abort(grpc.StatusCode.NOT_FOUND,
                          "etcdserver: requested lease not found")
            del self.st.leases[lid]
            for key in sorted(self.st.store.lease_keys.get(lid, ())):
                self.st.store.apply_txn(
                    Txn((), (("delete", key),), ()))
        return pb.LeaseRevokeResponse()

    def lease_keepalive(self, request_iterator: Iterator,
                        ctx) -> Iterator[pb.LeaseKeepAliveResponse]:
        for req in request_iterator:
            lid = int(req.ID)
            with self.st.lock:
                ttl = self.st.leases.get(lid, 0)
            yield pb.LeaseKeepAliveResponse(ID=lid, TTL=ttl)

    # ---- lock --------------------------------------------------------------

    def lock(self, req: pb.LockRequest, ctx) -> pb.LockResponse:
        import grpc
        name = req.name.decode("utf-8")
        lid = int(req.lease)
        my_key = f"{name}/{lid:016x}"
        deadline = time.monotonic() + 30
        while True:
            with self.st.lock:
                if lid not in self.st.leases:
                    ctx.abort(grpc.StatusCode.NOT_FOUND,
                              "etcdserver: requested lease not found")
                holders = self.st.store.range_prefix(name + "/")
                if not holders or all(h["key"] == my_key
                                      for h in holders):
                    self.st.store.apply_txn(
                        Txn((), (("put", my_key, lid, lid),), ()))
                    return pb.LockResponse(
                        header=pb.ResponseHeader(
                            revision=self.st.store.revision),
                        key=my_key.encode("utf-8"))
            if time.monotonic() > deadline:
                ctx.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                          "lock wait deadline")
            time.sleep(0.01)

    def unlock(self, req: pb.UnlockRequest, ctx) -> pb.UnlockResponse:
        with self.st.lock:
            self.st.store.apply_txn(
                Txn((), (("delete", req.key.decode("utf-8")),), ()))
        return pb.UnlockResponse()

    # ---- cluster / maintenance --------------------------------------------

    def _member_pb(self, mid: int) -> pb.Member:
        m = self.st.members[mid]
        return pb.Member(ID=mid, name=m.get("name", ""),
                         peerURLs=list(m.get("peerURLs", ())),
                         clientURLs=(list(m.get("clientURLs", ()))
                                     or ["grpc://local"]))

    def member_list(self, req, ctx) -> pb.MemberListResponse:
        with self.st.lock:
            return pb.MemberListResponse(
                members=[self._member_pb(mid)
                         for mid in sorted(self.st.members)])

    def member_add(self, req: pb.MemberAddRequest,
                   ctx) -> pb.MemberAddResponse:
        import grpc
        peer_urls = list(req.peerURLs)
        if not peer_urls:
            ctx.abort(grpc.StatusCode.INVALID_ARGUMENT,
                      "etcdserver: peerURL exists or is empty")
        mid = member_id_for_peer_urls(peer_urls)
        with self.st.lock:
            if mid in self.st.members:
                ctx.abort(grpc.StatusCode.ALREADY_EXISTS,
                          "etcdserver: member ID already exist")
            self.st.members[mid] = {"name": "", "peerURLs": peer_urls,
                                    "clientURLs": []}
            return pb.MemberAddResponse(
                header=pb.ResponseHeader(
                    revision=self.st.store.revision,
                    member_id=self.st.member_id),
                member=pb.Member(ID=mid, peerURLs=peer_urls),
                members=[self._member_pb(m)
                         for m in sorted(self.st.members)])

    def member_remove(self, req, ctx) -> pb.MemberRemoveResponse:
        import grpc
        mid = int(req.ID)
        with self.st.lock:
            if mid not in self.st.members:
                ctx.abort(grpc.StatusCode.NOT_FOUND,
                          "etcdserver: member not found")
            if len(self.st.members) == 1:
                ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "etcdserver: re-configuration failed due to "
                          "not enough started members")
            del self.st.members[mid]
            return pb.MemberRemoveResponse(
                header=pb.ResponseHeader(
                    revision=self.st.store.revision,
                    member_id=self.st.member_id),
                members=[self._member_pb(m)
                         for m in sorted(self.st.members)])

    def status(self, req, ctx) -> pb.StatusResponse:
        with self.st.lock:
            rev = self.st.store.revision
            leader = self.st.leader_id()
            mid = self.st.member_id
        return pb.StatusResponse(
            header=pb.ResponseHeader(revision=rev, member_id=mid),
            leader=leader, raftTerm=2, raftIndex=rev,
            version="3.5.6-sim-gateway", dbSize=0)

    def defragment(self, req, ctx) -> pb.DefragmentResponse:
        return pb.DefragmentResponse()

    # ---- watch (bidi stream) ----------------------------------------------

    def watch(self, request_iterator: Iterator,
              ctx) -> Iterator[pb.WatchResponse]:
        first = next(request_iterator)
        create = first.create_request
        key = create.key.decode("utf-8")
        start = int(create.start_revision)
        yield pb.WatchResponse(created=True, watch_id=1)
        last = max(0, start - 1)
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline and ctx.is_active():
            with self.st.lock:
                try:
                    events = [e for e in
                              self.st.store.events_since(last + 1)
                              if e.key == key and e.revision > last]
                except SimError as e:
                    # compaction past the watch: cancel the stream with
                    # the compact horizon so the client restarts there
                    # (real etcd's watch cancel semantics). Anything
                    # else is a real bug and must propagate
                    if e.type != "compacted":
                        raise
                    yield pb.WatchResponse(
                        canceled=True, watch_id=1,
                        cancel_reason=(
                            "etcdserver: mvcc: required revision has "
                            "been compacted"),
                        compact_revision=int(
                            getattr(e, "compact_revision", None)
                            or self.st.store.compact_revision))
                    return
                rev = self.st.store.revision
            if events:
                last = max(e.revision for e in events)
                resp = pb.WatchResponse(
                    header=pb.ResponseHeader(revision=rev), watch_id=1)
                for e in events:
                    ev = resp.events.add()
                    ev.type = (pb.Event.DELETE if e.type == "delete"
                               else pb.Event.PUT)
                    if e.kv:
                        ev.kv.CopyFrom(_kv_wire(e.kv))
                    else:
                        ev.kv.key = e.key.encode("utf-8")
                        ev.kv.mod_revision = e.revision
                    if e.prev_kv:
                        ev.prev_kv.CopyFrom(_kv_wire(e.prev_kv))
                yield resp
            time.sleep(0.02)


def serve_grpc(port: int = 0, state: GatewayState = None):
    """Start the gRPC gateway on localhost:port (0 = ephemeral);
    returns (server, state, bound_port). Caller stop()s the server
    when done. Pass `state` to serve a pre-configured cluster
    surface."""
    import grpc
    from concurrent import futures

    state = state if state is not None else GatewayState()
    svc = _Services(state)

    def unary(fn, req_cls):
        return grpc.unary_unary_rpc_method_handler(
            fn, request_deserializer=req_cls.FromString,
            response_serializer=lambda m: m.SerializeToString())

    def stream(fn, req_cls):
        return grpc.stream_stream_rpc_method_handler(
            fn, request_deserializer=req_cls.FromString,
            response_serializer=lambda m: m.SerializeToString())

    handlers = [
        grpc.method_handlers_generic_handler("etcdserverpb.KV", {
            "Range": unary(svc.range, pb.RangeRequest),
            "Txn": unary(svc.txn, pb.TxnRequest),
            "Compact": unary(svc.compact, pb.CompactionRequest),
        }),
        grpc.method_handlers_generic_handler("etcdserverpb.Lease", {
            "LeaseGrant": unary(svc.lease_grant, pb.LeaseGrantRequest),
            "LeaseRevoke": unary(svc.lease_revoke,
                                 pb.LeaseRevokeRequest),
            "LeaseKeepAlive": stream(svc.lease_keepalive,
                                     pb.LeaseKeepAliveRequest),
        }),
        grpc.method_handlers_generic_handler("etcdserverpb.Watch", {
            "Watch": stream(svc.watch, pb.WatchRequest),
        }),
        grpc.method_handlers_generic_handler("etcdserverpb.Cluster", {
            "MemberList": unary(svc.member_list, pb.MemberListRequest),
            "MemberAdd": unary(svc.member_add, pb.MemberAddRequest),
            "MemberRemove": unary(svc.member_remove,
                                  pb.MemberRemoveRequest),
        }),
        grpc.method_handlers_generic_handler(
            "etcdserverpb.Maintenance", {
                "Status": unary(svc.status, pb.StatusRequest),
                "Defragment": unary(svc.defragment,
                                    pb.DefragmentRequest),
            }),
        grpc.method_handlers_generic_handler("v3lockpb.Lock", {
            "Lock": unary(svc.lock, pb.LockRequest),
            "Unlock": unary(svc.unlock, pb.UnlockRequest),
        }),
    ]
    # watch and lock handlers PIN a worker for their whole stream /
    # spin duration (up to 300 s / 30 s), so the pool must comfortably
    # exceed the harness's worst-case concurrent watcher count — the
    # HTTP gateway's ThreadingHTTPServer is effectively unbounded
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=128),
        options=[("grpc.so_reuseport", 0)])
    for h in handlers:
        server.add_generic_rpc_handlers((h,))
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    server.start()
    return server, state, bound
