"""The simulated etcd cluster: replicated MVCC over a raft-style log.

This is the system-under-test substrate replacing the reference's real
5-node etcd cluster (``db.clj`` installs/starts real binaries; we simulate).
Faithfulness targets (SURVEY §2.2 "etcd server" row):

- **Consensus**: leader election with the Raft voting restriction (votes
  only for candidates with an up-to-date log) and the leader-commits-only-
  its-own-term rule (noop entry on election), so the cluster is
  linearizable by default — the register workload must PASS against a
  healthy or crash-faulted cluster, and genuinely LOSE data only in the
  scenarios real etcd does (e.g. majority kill with lazyfs-style loss of
  unfsynced WAL tail, cf. db.clj:264-267).
- **Durability model**: per-node WAL + snapshot byte buffers with record
  CRCs (wal.py). With ``unsafe_no_fsync`` (the reference passes
  ``--unsafe-no-fsync``, db.clj:88) appends are durable only up to the
  last snapshot/fsync; a lazyfs kill drops the unfsynced tail. Corruption
  faults flip bits / truncate these buffers; replay panics on a damaged
  committed record (log-file-pattern checker bait, etcd.clj:134-140).
- **Client semantics**: linearizable ops execute at the leader (followers
  forward); serializable reads are node-local (stale under partition);
  leases are leader-timed and reset to full TTL on leader change (the
  etcd behavior that makes locks unsafe, lock.clj); watches stream each
  node's *applied* events in revision order.
- **Faults**: kill/start (with optional lost unfsynced writes), pause/
  resume (SIGSTOP: node unreachable, connections hang), partitions
  (node<->node only; clients always reach nodes, like jepsen's control
  node), clock skew (shifts lease expiry), membership add/remove,
  WAL/snapshot corruption, compaction, defrag.

Everything runs on the deterministic virtual-time loop.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..runner.sim import SimLoop, Future, Event as SimEvent, SECOND
from .errors import SimError
from .store import Store, Txn, Event
from . import wal as walmod

logger = logging.getLogger("jepsen_etcd_tpu.sut")

MS = 1_000_000  # virtual ns


def member_id(name: str) -> int:
    """Stable 64-bit member id for a node name (etcd derives member ids
    by hashing peer URLs; grow always mints fresh names, so a name-hash
    is equally unique and — unlike real etcd — reproducible across
    seeds)."""
    import hashlib
    return int.from_bytes(
        hashlib.sha1(name.encode()).digest()[:8], "big") & (2 ** 63 - 1)


@dataclass
class ClusterConfig:
    election_timeout: int = 1000 * MS     # etcd default 1s
    heartbeat_interval: int = 100 * MS    # etcd default 100ms
    repl_delay: tuple = (1 * MS, 5 * MS)  # node->node replication latency
    rpc_delay: tuple = (1 * MS, 3 * MS)   # client->node latency (per leg)
    snapshot_count: int = 100             # reference stress default
    unsafe_no_fsync: bool = False         # etcd default: fsync on; the
                                          # reference flips it only when
                                          # --unsafe-no-fsync is passed
                                          # (etcd.clj:204, db.clj:96)
    lazyfs: bool = False                  # lose unfsynced writes on kill
    corrupt_check: bool = False           # record per-node state hashes at
                                          # fixed applied indexes so the
                                          # corruption monitor can compare
                                          # them (etcd.clj:164, db.clj:97-99)
    tick: int = 50 * MS                   # scheduler granularity


#: with corrupt_check, fingerprint the applied store at every multiple of
#: this applied index — all nodes hash at the SAME indexes, the analog of
#: etcd's hashKV-at-compact-revision peer comparison
FP_EVERY = 64
#: bound the per-node fingerprint ledger
FP_LEDGER_MAX = 256


@dataclass
class LogEntry:
    index: int
    term: int
    kind: str      # "txn" | "noop" | "compact" | "member_add" |
                   # "member_remove" | "lease_grant" | "lease_revoke"
    payload: Any = None


class Node:
    def __init__(self, name: str, cluster: "Cluster", membership: list):
        self.name = name
        self.cluster = cluster
        self.alive = False
        self.paused = False
        self.removed = False
        self.clock_offset = 0
        # raft volatile
        self.term = 0
        self.voted_for: Optional[str] = None
        self.role = "follower"
        self.leader_hint: Optional[str] = None
        self.election_deadline = 0
        self.last_quorum_contact = 0
        # campaign state (message-level elections): votes received for
        # the current campaign; campaign_id guards stale vote responses
        self.votes: set = set()
        self.campaign_id = 0
        # log: entries [log_start..]; index 0 is a sentinel before start
        self.log: list[LogEntry] = []
        self.log_start = 1      # raft index of log[0]
        self.snap_index = 0
        self.snap_term = 0
        self.commit_index = 0
        self.store = Store()
        self.membership: list[str] = list(membership)
        self.leases: dict[int, int] = {}     # lease id -> ttl (applied state)
        # leader volatile
        self.send_inflight: set = set()  # peers with a sleeping _send_append
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}
        self.lease_expiry: dict[int, int] = {}
        self.waiters: dict[int, tuple[int, Future]] = {}  # index->(term,fut)
        # durability ("files" on disk). RecordFiles hold records as
        # objects and only materialize framed CRC bytes when a
        # corruption fault touches them — value-carrying records made
        # per-append pickling O(history²) on append-heavy workloads
        self.wal = walmod.RecordFile()
        self.snap = walmod.RecordFile()
        self.applied_since_snap = 0
        # observability
        self.etcd_log: list[str] = []
        self.resume_event: Optional[SimEvent] = None
        self.watchers: list = []  # Watcher objects served by this node
        self.store_applied_index = 0
        # corrupt-check: applied index -> state fingerprint, recorded at
        # FP_EVERY multiples (deterministic apply means every healthy
        # node records the same value at the same index)
        self.fp_ledger: dict[int, int] = {}

    # ---- small helpers ----------------------------------------------------

    @property
    def loop(self) -> SimLoop:
        return self.cluster.loop

    def clock(self) -> int:
        return self.loop.now + self.clock_offset

    def log_line(self, msg: str) -> None:
        self.etcd_log.append(
            f"{{\"ts\":{self.loop.now / SECOND:.3f},\"msg\":{msg!r}}}")

    def last_index(self) -> int:
        return self.log_start + len(self.log) - 1 if self.log else self.snap_index

    def last_term(self) -> int:
        return self.log[-1].term if self.log else self.snap_term

    def entry(self, index: int) -> Optional[LogEntry]:
        i = index - self.log_start
        return self.log[i] if 0 <= i < len(self.log) else None

    def majority(self) -> int:
        return len(self.membership) // 2 + 1

    def reset_election_deadline(self) -> None:
        jitter = self.loop.rng.randint(0, self.cluster.cfg.election_timeout)
        self.election_deadline = (self.loop.now +
                                  self.cluster.cfg.election_timeout + jitter)

    # ---- durability -------------------------------------------------------

    def wal_append(self, e: LogEntry) -> None:
        # fsync-per-append unless --unsafe-no-fsync, as etcd does
        self.wal.append((e.index, e.term, e.kind, e.payload),
                        sync=not self.cluster.cfg.unsafe_no_fsync)

    def wal_rewrite(self, entries: list) -> None:
        """Wholesale WAL rewrite (conflict truncation, recovery
        re-encode): fsynced like etcd's unless --unsafe-no-fsync."""
        self.wal.set_records(
            [(e.index, e.term, e.kind, e.payload) for e in entries],
            sync=not self.cluster.cfg.unsafe_no_fsync)

    def fsync(self) -> None:
        self.wal.fsync()
        self.snap.fsync()

    def maybe_snapshot(self) -> None:
        if self.applied_since_snap < self.cluster.cfg.snapshot_count:
            return
        applied = self.commit_index
        self.snap_index = applied
        ent = self.entry(applied)
        self.snap_term = ent.term if ent else self.term
        snap = (applied, self.snap_term, self.store.clone(),
                list(self.membership), dict(self.leases))
        self.snap.set_records([snap], sync=True)
        # drop the log prefix; rebuild the WAL from the snapshot point
        keep = self.log[max(0, applied + 1 - self.log_start):]
        self.log = keep
        self.log_start = applied + 1
        self.wal_rewrite(keep)
        self.fsync()  # etcd fsyncs snapshots even with --unsafe-no-fsync
        self.applied_since_snap = 0
        self.log_line(f"saved snapshot at index {applied}")

    # ---- state machine ----------------------------------------------------

    def apply_up_to_commit(self) -> None:
        while self.store_applied_index < self.commit_index:
            idx = self.store_applied_index + 1
            e = self.entry(idx)
            if e is None:
                break  # entry compacted away / missing (snapshot pending)
            self._apply(e)
            self.store_applied_index = idx
            self.applied_since_snap += 1
            if self.cluster.cfg.corrupt_check and idx % FP_EVERY == 0:
                self.fp_ledger[idx] = self.store.state_fingerprint()
                while len(self.fp_ledger) > FP_LEDGER_MAX:
                    self.fp_ledger.pop(next(iter(self.fp_ledger)))
        self.maybe_snapshot()

    def _apply(self, e: LogEntry) -> None:
        result = None
        if e.kind == "txn":
            result = self.store.apply_txn(e.payload)
            if result["events"]:
                self._notify_watchers(result["events"])
        elif e.kind == "compact":
            try:
                self.store.compact(e.payload)
            except SimError:
                pass
        elif e.kind == "member_add":
            if e.payload not in self.membership:
                self.membership.append(e.payload)
            self.log_line(f"added member {e.payload}")
        elif e.kind == "member_remove":
            if e.payload in self.membership:
                self.membership.remove(e.payload)
            self.log_line(f"removed member {e.payload}")
            if e.payload == self.name:
                self.removed = True
                self.role = "follower"
            else:
                # conf-change broadcast: the removed member learns and
                # shuts its raft ("raft: stopped", client.clj:322-323)
                victim = self.cluster.nodes.get(e.payload)
                if victim is not None and victim.alive:
                    victim.removed = True
                    victim.role = "follower"
                    victim.membership = [m for m in victim.membership
                                         if m != e.payload]
                    victim.log_line("raft: stopped (removed from cluster)")
        elif e.kind == "lease_grant":
            lid, ttl = e.payload
            self.leases[lid] = ttl
            if self.role == "leader":
                self.lease_expiry.setdefault(lid, self.clock() + ttl)
        elif e.kind == "lease_revoke":
            lid = e.payload
            self.leases.pop(lid, None)
            self.lease_expiry.pop(lid, None)
            keys = sorted(self.store.lease_keys.get(lid, set()))
            if keys:
                res = self.store.apply_txn(
                    Txn((), tuple(("delete", k) for k in keys), ()))
                if res["events"]:
                    self._notify_watchers(res["events"])
            self.store.lease_keys.pop(lid, None)
        # resolve the proposer's waiter
        w = self.waiters.pop(e.index, None)
        if w is not None:
            wterm, fut = w
            if wterm == e.term:
                fut.set_result(result)
            else:
                fut.set_exception(SimError("leader-changed",
                                           "entry overwritten"))

    def _notify_watchers(self, events: list[Event]) -> None:
        for w in list(self.watchers):
            w.feed(events)

class Watcher:
    """A watch stream served by one node (client.clj:663-693 surface)."""

    def __init__(self, node: Node, key: str, from_rev: int,
                 on_events: Callable, on_error: Callable,
                 prefix: bool = False):
        self.node = node
        self.key = key
        self.prefix = prefix
        self.next_rev = from_rev
        self.on_events = on_events
        self.on_error = on_error
        self.closed = False
        # A watch is ONE ordered stream: deliveries form a FIFO chain so
        # random per-batch latencies can never reorder events
        # (the nonmonotonic-revision check at watch.clj:161-177 relies on
        # stream order; reordering here would be a false SUT bug).
        self._outbox: list[list[Event]] = []
        self._draining = False

    def matches(self, ev: Event) -> bool:
        return (ev.key.startswith(self.key) if self.prefix
                else ev.key == self.key)

    def feed(self, events: list[Event]) -> None:
        if self.closed:
            return
        evs = [e for e in events
               if self.matches(e) and e.revision >= self.next_rev]
        if not evs:
            return
        self.next_rev = max(e.revision for e in evs) + 1
        self._outbox.append(evs)
        if not self._draining:
            self._draining = True
            delay = self.node.cluster.msg_delay(
                self.node.cluster.cfg.rpc_delay)
            self.node.loop.call_later(delay, self._drain)

    def _drain(self) -> None:
        if self.closed or not self.node.alive:
            self._draining = False
            return  # stream broken; kill_node cancels with an error
        if self.node.paused:
            # SIGSTOP: the kernel buffers the stream; deliver after resume.
            self.node.loop.call_later(self.node.cluster.cfg.tick,
                                      self._drain)
            return
        while self._outbox:
            self.on_events(self._outbox.pop(0))
        self._draining = False

    def cancel(self, error: Optional[SimError] = None) -> None:
        if self.closed:
            return
        self.closed = True
        if self in self.node.watchers:
            self.node.watchers.remove(self)
        if error is not None:
            self.on_error(error)


class Cluster:
    """The simulated cluster + fault API. One instance per test."""

    def __init__(self, loop: SimLoop, node_names: list[str],
                 cfg: Optional[ClusterConfig] = None):
        self.loop = loop
        self.cfg = cfg or ClusterConfig()
        self.initial_names = list(node_names)
        self.nodes: dict[str, Node] = {
            n: Node(n, self, node_names) for n in node_names}
        # blocked link set: frozensets block both directions, ordered
        # (src, dst) tuples block only src -> dst (one-way partitions —
        # the same encoding net/plane.py uses in local mode)
        self.blocked_pairs: set = set()
        # (lo_ns, hi_ns) extra per-message-leg delay when a latency
        # fault is active; None = no fault and NO extra rng draw, so
        # fault-free seeded histories stay bit-identical
        self.net_latency: Optional[tuple[int, int]] = None
        self.running = False
        self._tick_task = None
        self.next_lease_id = 0x70000000
        self.tracer = None  # runner.trace.NetTrace when --tcpdump is set
        # corrupt-check monitor state: confirmed divergences + dedupe keys
        self.corruption_alarms: list[dict] = []
        self._alarm_keys: set = set()

    def _trace(self, kind: str, src: str, dst: str, **info: Any) -> None:
        if self.tracer is not None:
            self.tracer.record(kind, src, dst, **info)

    # ---- lifecycle --------------------------------------------------------

    def launch(self) -> None:
        self.running = True
        for n in self.nodes.values():
            if not n.alive:
                self.start_node(n.name, fresh=True)
        self._tick_task = self.loop.spawn(self._tick_loop(), "cluster-tick")

    def shutdown(self) -> None:
        self.running = False
        for n in self.nodes.values():
            n.alive = False

    async def _tick_loop(self) -> None:
        while self.running:
            await self.loop.sleep(self.cfg.tick)
            for n in list(self.nodes.values()):
                if not n.alive or n.paused or n.removed:
                    continue
                if n.role == "leader":
                    self._leader_tick(n)
                elif self.loop.now >= n.election_deadline:
                    self._start_election(n)

    # ---- connectivity -----------------------------------------------------

    def reachable(self, a: str, b: str) -> bool:
        """Can a message leg travel a -> b right now? Callers pass the
        actual direction per leg (request legs src->dst, response legs
        dst->src), so one-way blocks drop exactly one side."""
        if a == b:
            return True
        na, nb = self.nodes.get(a), self.nodes.get(b)
        if na is None or nb is None:
            return False
        if not (na.alive and nb.alive) or na.paused or nb.paused:
            return False
        return (frozenset((a, b)) not in self.blocked_pairs
                and (a, b) not in self.blocked_pairs)

    def msg_delay(self, base: tuple) -> int:
        """One message-leg delay draw. The injected-latency draw
        happens ONLY while a latency fault is active: the rng stream of
        fault-free runs is untouched (same-seed bit-identity)."""
        d = self.loop.rng.randint(*base)
        if self.net_latency is not None:
            d += self.loop.rng.randint(*self.net_latency)
        return d

    def visible_majority(self, node: Node) -> bool:
        peers = [m for m in node.membership]
        up = sum(1 for m in peers if self.reachable(node.name, m))
        return up >= node.majority()

    # ---- elections & replication ------------------------------------------

    def _start_election(self, cand: Node) -> None:
        """Campaign via message-delayed RequestVote RPCs.

        Requests and responses travel as separate delayed messages (like
        ``_send_append``), so split votes, stale candidates, interleaved
        campaigns, and vote messages lost to partitions/kills all occur —
        the raft schedule surface the reference gets for free by running
        real etcd (db.clj:72-100). The RPC carries the candidate's log
        position captured at send time, per the raft paper.
        """
        cand.term += 1
        cand.voted_for = cand.name
        cand.role = "candidate"
        cand.reset_election_deadline()
        cand.campaign_id += 1
        cand.votes = {cand.name}
        cand.log_line(f"campaigning at term {cand.term}")
        last_term, last_index = cand.last_term(), cand.last_index()
        for m in cand.membership:
            if m == cand.name:
                continue
            self.loop.spawn(
                self._request_vote(cand, m, cand.term, cand.campaign_id,
                                   last_term, last_index), "vote")
        if len(cand.votes) >= cand.majority():   # single-node cluster
            self._become_leader(cand)

    async def _request_vote(self, cand: Node, peer_name: str, term: int,
                            campaign_id: int, last_term: int,
                            last_index: int) -> None:
        # request leg: delivered only if both ends are up and connected
        # at arrival time (same drop model as _send_append)
        await self.loop.sleep(self.msg_delay(self.cfg.repl_delay))
        peer = self.nodes.get(peer_name)
        if (peer is None or peer.removed
                or not self.reachable(cand.name, peer_name)):
            self._trace("vote-req", cand.name, peer_name, term=term,
                        delivered=False)
            return
        self._trace("vote-req", cand.name, peer_name, term=term,
                    delivered=True)
        granted = False
        if peer.term <= term:
            if peer.term < term:
                peer.term = term
                peer.voted_for = None
                if peer.role != "follower":
                    peer.role = "follower"
                    peer.log_line(f"stepping down: saw term {term}")
            up_to_date = (last_term, last_index) >= \
                         (peer.last_term(), peer.last_index())
            if peer.voted_for in (None, cand.name) and up_to_date:
                peer.voted_for = cand.name
                peer.reset_election_deadline()
                granted = True
        resp_term = peer.term
        # response leg
        await self.loop.sleep(self.msg_delay(self.cfg.repl_delay))
        delivered = self.reachable(peer_name, cand.name)
        self._trace("vote-resp", peer_name, cand.name, term=resp_term,
                    granted=granted, delivered=delivered)
        if not delivered:
            return
        if resp_term > cand.term:
            # may already have won and accepted proposals: fail their
            # waiters like every other step-down site
            cand.term = resp_term
            cand.role = "follower"
            cand.voted_for = None
            cand.reset_election_deadline()
            self._fail_waiters(cand, SimError(
                "leader-changed", "higher term in vote response"))
            return
        if cand.role != "candidate" or cand.campaign_id != campaign_id \
                or cand.term != term:
            return  # stale response: a newer campaign, or already decided
        if granted:
            cand.votes.add(peer_name)
            if len(cand.votes) >= cand.majority():
                self._become_leader(cand)

    def _become_leader(self, n: Node) -> None:
        n.role = "leader"
        n.leader_hint = n.name
        n.last_quorum_contact = self.loop.now
        n.next_index = {m: n.last_index() + 1 for m in n.membership}
        n.match_index = {m: 0 for m in n.membership}
        # fresh full TTL for every applied lease (etcd leader-change behavior
        # — the reason lock tests must fail, lock.clj)
        n.lease_expiry = {lid: n.clock() + ttl
                          for lid, ttl in n.leases.items()}
        n.log_line(f"elected leader at term {n.term}")
        logger.debug("%s elected leader term %d", n.name, n.term)
        self._append_entry(n, "noop", None)
        self._leader_tick(n)

    def _append_entry(self, leader: Node, kind: str, payload: Any,
                      fut: Optional[Future] = None) -> LogEntry:
        e = LogEntry(index=leader.last_index() + 1, term=leader.term,
                     kind=kind, payload=payload)
        leader.log.append(e)
        leader.wal_append(e)
        if fut is not None:
            leader.waiters[e.index] = (e.term, fut)
        self._replicate_now(leader)
        return e

    def _replicate_now(self, leader: Node) -> None:
        for m in leader.membership:
            if m == leader.name or m in leader.send_inflight:
                # a sender is already sleeping its repl_delay; it reads the
                # log at wake time, so it will carry entries appended now
                continue
            leader.send_inflight.add(m)
            self.loop.spawn(self._send_append(leader, m), "repl")
        self._advance_commit(leader)

    def _leader_tick(self, leader: Node) -> None:
        # check-quorum: a partitioned leader steps down
        if not self.visible_majority(leader):
            if (self.loop.now - leader.last_quorum_contact >
                    self.cfg.election_timeout):
                leader.role = "follower"
                leader.reset_election_deadline()
                leader.log_line("lost quorum; stepping down")
                self._fail_waiters(leader, SimError(
                    "leader-changed", "lost quorum"))
                return
        else:
            leader.last_quorum_contact = self.loop.now
        self._replicate_now(leader)
        self._expire_leases(leader)

    async def _send_append(self, leader: Node, peer_name: str) -> None:
        try:
            await self.loop.sleep(self.msg_delay(self.cfg.repl_delay))
        finally:
            # past the coalescing window: appends after this point need
            # (and will get) a fresh sender. Cleared in finally — a
            # cancel thrown at the sleep suspension point must not leak
            # the coalescing flag, or _replicate_now would never spawn
            # another sender for this peer
            leader.send_inflight.discard(peer_name)
        peer = self.nodes.get(peer_name)
        if (peer is None or leader.role != "leader" or not leader.alive
                or not self.reachable(leader.name, peer_name)
                or peer.removed):
            self._trace("append", leader.name, peer_name, term=leader.term,
                        delivered=False)
            return
        self._trace("append", leader.name, peer_name, term=leader.term,
                    commit=leader.commit_index, delivered=True)
        if peer.term > leader.term:
            leader.term = peer.term
            leader.role = "follower"
            leader.voted_for = None
            self._fail_waiters(leader, SimError("leader-changed",
                                                "higher term seen"))
            return
        if peer.term < leader.term:
            peer.term = leader.term
            peer.voted_for = None
        peer.role = "follower"
        peer.leader_hint = leader.name
        peer.reset_election_deadline()
        ni = leader.next_index.get(peer_name, leader.last_index() + 1)
        if ni < leader.log_start:
            # peer too far behind: install snapshot
            self._install_snapshot(leader, peer)
            ni = leader.log_start
        # log-matching check at ni-1
        prev_idx = ni - 1
        if prev_idx <= peer.snap_index or prev_idx == 0:
            ok = True  # at/below peer's snapshot: that prefix is committed
        else:
            pe = peer.entry(prev_idx)
            if pe is None:
                ok = False  # peer's log too short: back up
            else:
                le = leader.entry(prev_idx)
                expected = le.term if le is not None else (
                    leader.snap_term if prev_idx == leader.snap_index
                    else None)
                ok = expected is not None and pe.term == expected
        if not ok:
            leader.next_index[peer_name] = max(1, ni - 1)
            return
        # append entries from ni (log is contiguous from log_start, so the
        # tail is a slice — a full-log scan here is O(ops^2) over a run)
        entries = leader.log[max(0, ni - leader.log_start):]
        if entries:
            # truncate conflicts
            first = entries[0].index
            conflict = None
            for e in entries:
                pe = peer.entry(e.index)
                if pe is not None and pe.term != e.term:
                    conflict = e.index
                    break
            if conflict is not None:
                kept = [e for e in peer.log if e.index < conflict]
                dropped = [e for e in peer.log if e.index >= conflict]
                peer.log = kept
                for d in dropped:
                    w = peer.waiters.pop(d.index, None)
                    if w is not None:
                        w[1].set_exception(SimError("leader-changed",
                                                    "entry overwritten"))
                peer.wal_rewrite(peer.log)
            for e in entries:
                if peer.entry(e.index) is None:
                    peer.log.append(LogEntry(e.index, e.term, e.kind,
                                             e.payload))
                    peer.wal_append(peer.log[-1])
            leader.next_index[peer_name] = entries[-1].index + 1
            leader.match_index[peer_name] = entries[-1].index
        else:
            leader.match_index[peer_name] = max(
                leader.match_index.get(peer_name, 0),
                min(ni - 1, leader.last_index()))
        # propagate commit index
        self._advance_commit(leader)
        new_commit = min(leader.commit_index, peer.last_index())
        if new_commit > peer.commit_index:
            peer.commit_index = new_commit
            peer.apply_up_to_commit()

    def _install_snapshot(self, leader: Node, peer: Node) -> None:
        self._trace("snapshot", leader.name, peer.name,
                    index=leader.snap_index, delivered=True)
        snap_items, err = leader.snap.read()
        if err or not snap_items:
            # leader snapshot bytes damaged: send live state (etcd would
            # alarm; we keep the cluster moving and log it)
            leader.log_line("snapshot send from live state")
            peer.store = leader.store.clone()
            peer.membership = list(leader.membership)
            peer.leases = dict(leader.leases)
            peer.snap_index, peer.snap_term = leader.store_applied_index, leader.term
            peer.store_applied_index = leader.store_applied_index
            peer.log = []
            peer.log_start = peer.snap_index + 1
            peer.commit_index = peer.snap_index
        else:
            idx, term, store, membership, leases = snap_items[0]
            peer.store = store.clone()
            peer.membership = list(membership)
            peer.leases = dict(leases)
            peer.snap_index, peer.snap_term = idx, term
            peer.store_applied_index = idx
            peer.log = []
            peer.log_start = idx + 1
            peer.commit_index = idx
        # re-save from the received state — snapshot transfer is
        # CRC-verified in etcd, so damaged leader bytes must not propagate
        peer.snap.set_records([
            (peer.snap_index, peer.snap_term, peer.store.clone(),
             list(peer.membership), dict(peer.leases))], sync=True)
        peer.wal.clear()
        peer.fsync()
        peer.applied_since_snap = 0
        peer.log_line(f"installed snapshot at index {peer.snap_index}")
        # a snapshot replaces the store without applying the skipped
        # entries, so sync watchers from the new store's event history
        # (etcd's watchableStore catches unsynced watchers up from the
        # MVCC backend; only compaction can actually lose them events)
        for w in list(peer.watchers):
            try:
                backlog = peer.store.events_since(w.next_rev)
            except SimError as e:
                w.cancel(e)
                continue
            if backlog:
                w.feed(backlog)

    def _advance_commit(self, leader: Node) -> None:
        if leader.role != "leader":
            return
        for idx in range(leader.last_index(), leader.commit_index, -1):
            e = leader.entry(idx)
            if e is None or e.term != leader.term:
                continue  # only commit entries of own term by counting
            votes = 0
            for m in leader.membership:
                if m == leader.name:
                    votes += 1
                elif leader.match_index.get(m, 0) >= idx:
                    votes += 1
            if votes >= leader.majority():
                leader.commit_index = idx
                leader.apply_up_to_commit()
                break

    def _fail_waiters(self, n: Node, err: SimError) -> None:
        for idx, (_, fut) in list(n.waiters.items()):
            fut.set_exception(err)
        n.waiters.clear()

    def _expire_leases(self, leader: Node) -> None:
        now = leader.clock()
        for lid, deadline in list(leader.lease_expiry.items()):
            if now >= deadline and lid in leader.leases:
                leader.lease_expiry.pop(lid, None)
                leader.log_line(f"lease {lid:x} expired")
                self.loop.spawn(self._propose_silent(
                    leader.name, "lease_revoke", lid), "lease-expire")

    async def _propose_silent(self, leader_name: str, kind: str,
                              payload: Any) -> None:
        try:
            await self.propose(leader_name, kind, payload)
        except SimError:
            pass

    # ---- proposals (leader-side) ------------------------------------------

    async def propose(self, node_name: str, kind: str, payload: Any) -> Any:
        """Propose an entry at node (must be leader); resolves at apply."""
        n = self.nodes[node_name]
        if n.role != "leader" or not n.alive:
            raise SimError("not-leader", node_name)
        fut = self.loop.future()
        self._append_entry(n, kind, payload, fut)
        return await fut

    def current_leader_visible(self, from_node: Node) -> Optional[Node]:
        """The leader as discoverable from this node (via its raft links)."""
        # direct knowledge
        for name in [from_node.leader_hint] + list(from_node.membership):
            if name is None:
                continue
            ln = self.nodes.get(name)
            if (ln is not None and ln.alive and not ln.paused
                    and ln.role == "leader"
                    and self.reachable(from_node.name, name)):
                return ln
        return None

    # ---- client RPC surface ------------------------------------------------

    async def _enter(self, node_name: str) -> Node:
        """Client dial + request leg."""
        n = self.nodes.get(node_name)
        if n is None:
            raise SimError("unavailable", f"unknown node {node_name}")
        await self.loop.sleep(self.msg_delay(self.cfg.rpc_delay))
        if not n.alive:
            raise SimError("connect-failed", node_name)
        if n.removed:
            raise SimError("raft-stopped", node_name)
        if n.paused:
            # SIGSTOP: the TCP connection hangs; wait for resume
            if n.resume_event is None:
                n.resume_event = SimEvent(self.loop)
            await n.resume_event.wait()
            if not n.alive:
                raise SimError("connect-failed", node_name)
        return n

    async def _at_leader(self, node: Node) -> Node:
        """Forward to the leader, waiting through elections (until the
        caller's timeout cancels us)."""
        while True:
            if node.role == "leader":
                return node
            leader = self.current_leader_visible(node)
            if leader is not None:
                await self.loop.sleep(self.msg_delay(self.cfg.repl_delay))
                return leader
            await self.loop.sleep(self.cfg.heartbeat_interval)
            if not node.alive:
                raise SimError("unavailable", node.name)

    async def kv_txn(self, node_name: str, txn: Txn) -> dict:
        """Linearizable If/Then/Else transaction (client.clj:464-485)."""
        n = await self._enter(node_name)
        leader = await self._at_leader(n)
        result = await self.propose(leader.name, "txn", txn)
        await self.loop.sleep(self.msg_delay(self.cfg.rpc_delay))
        return result

    async def kv_read(self, node_name: str, key: str,
                      serializable: bool = False) -> dict:
        """Reads: serializable = node-local (stale allowed, register.clj:26-28
        with :serializable); default linearizable via leader read-index."""
        n = await self._enter(node_name)
        if serializable:
            return {"kv": n.store.get(key), "revision": n.store.revision}
        leader = await self._at_leader(n)
        await self._read_index(leader)
        out = {"kv": leader.store.get(key), "revision": leader.store.revision}
        await self.loop.sleep(self.msg_delay(self.cfg.rpc_delay))
        return out

    def _committed_own_term(self, leader: Node) -> bool:
        """Has this leader committed an entry of its OWN term? Until the
        election noop commits, the leader's commit_index may lag entries
        the PREVIOUS leader already acked (they are in this log by the
        election restriction, but commit knowledge travels with later
        appends) — serving reads before then returns applied state from
        before those acks: a stale linearizable read. etcd refuses
        ReadIndex until then (raft §8 / etcd server apply loop); found
        in-harness by the register checker as a real violation (r5): a
        2.3 s stale window after a kill+partition churn."""
        ci = leader.commit_index
        if ci <= leader.snap_index:
            term = leader.snap_term if ci == leader.snap_index else 0
        else:
            e = leader.entry(ci)
            term = e.term if e is not None else 0
        return term == leader.term

    async def _read_index(self, leader: Node) -> None:
        """Quorum round before serving a linearizable read.

        This is a real heartbeat exchange, not just a reachability count:
        each contacted peer reports its term, so a stale leader (e.g. one
        just resumed from SIGSTOP while a successor was elected) is deposed
        here instead of serving a stale read as linearizable. A NEW
        leader additionally refuses until its own-term noop commits
        (_committed_own_term) — before that its applied state may miss
        entries its predecessor acked.
        """
        while True:
            await self.loop.sleep(self.msg_delay(self.cfg.repl_delay))
            if not leader.alive:
                raise SimError("unavailable", leader.name)
            if leader.role != "leader":
                raise SimError("leader-changed", leader.name)
            if not self._committed_own_term(leader):
                await self.loop.sleep(self.cfg.heartbeat_interval)
                continue
            acks = 0
            for m in leader.membership:
                if m == leader.name:
                    acks += 1
                    continue
                peer = self.nodes.get(m)
                if peer is None or not self.reachable(leader.name, m):
                    continue
                if peer.term > leader.term:
                    leader.term = peer.term
                    leader.role = "follower"
                    leader.voted_for = None
                    self._fail_waiters(leader, SimError(
                        "leader-changed", "higher term seen on read-index"))
                    raise SimError("leader-changed", leader.name)
                acks += 1
            if acks >= leader.majority():
                return
            await self.loop.sleep(self.cfg.heartbeat_interval)

    async def range_read(self, node_name: str, prefix: str,
                         serializable: bool = False) -> list[dict]:
        n = await self._enter(node_name)
        if serializable:
            return n.store.range_prefix(prefix)
        leader = await self._at_leader(n)
        await self._read_index(leader)
        return leader.store.range_prefix(prefix)

    # ---- leases ------------------------------------------------------------

    async def lease_grant(self, node_name: str, ttl_ns: int) -> int:
        n = await self._enter(node_name)
        leader = await self._at_leader(n)
        self.next_lease_id += self.loop.rng.randint(1, 1000)
        lid = self.next_lease_id
        await self.propose(leader.name, "lease_grant", (lid, ttl_ns))
        return lid

    async def lease_revoke(self, node_name: str, lid: int) -> None:
        n = await self._enter(node_name)
        leader = await self._at_leader(n)
        if lid not in leader.leases:
            raise SimError("lease-not-found", f"{lid:x}")
        await self.propose(leader.name, "lease_revoke", lid)

    async def lease_keepalive(self, node_name: str, lid: int) -> int:
        """Refresh; returns granted ttl (client.clj:544-554 keepalive)."""
        n = await self._enter(node_name)
        leader = await self._at_leader(n)
        ttl = leader.leases.get(lid)
        if ttl is None:
            raise SimError("lease-not-found", f"{lid:x}")
        leader.lease_expiry[lid] = leader.clock() + ttl
        return ttl

    # ---- locks (etcd lock service semantics) --------------------------------

    async def lock(self, node_name: str, name: str, lid: int) -> str:
        """Acquire: create name/<lease> key, wait until first in queue."""
        n = await self._enter(node_name)
        leader = await self._at_leader(n)
        if lid not in leader.leases:
            raise SimError("lease-not-found", f"{lid:x}")
        key = f"__lock__/{name}/{lid:x}"
        await self.propose(leader.name, "txn", Txn(
            cmps=(("=", key, "version", 0),),
            then_ops=(("put", key, lid, lid),),
            else_ops=(("get", key),)))
        while True:
            waiters = await self.range_read(node_name,
                                           f"__lock__/{name}/")
            mine = [kv for kv in waiters if kv["key"] == key]
            if not mine:
                raise SimError("lease-not-found",
                               f"lock key lost (lease {lid:x} expired?)")
            if min(waiters, key=lambda kv: kv["create-revision"])["key"] == key:
                return key
            await self.loop.sleep(self.cfg.heartbeat_interval)

    async def unlock(self, node_name: str, lock_key: str) -> None:
        n = await self._enter(node_name)
        leader = await self._at_leader(n)
        res = await self.propose(leader.name, "txn",
                                 Txn((), (("delete", lock_key),), ()))
        deleted = res["results"][0][1]
        if not deleted:
            raise SimError("not-held", lock_key)

    # ---- watches ------------------------------------------------------------

    def watch(self, node_name: str, key: str, from_rev: int,
              on_events: Callable, on_error: Callable) -> Watcher:
        """Open a watch stream on a node from a revision
        (client.clj:663-693). Synchronous registration; catch-up events
        are delivered asynchronously."""
        n = self.nodes.get(node_name)
        if n is None or not n.alive:
            raise SimError("connect-failed", node_name)
        w = Watcher(n, key, from_rev, on_events, on_error)
        try:
            backlog = n.store.events_since(from_rev)
        except SimError as e:
            self.loop.call_soon(on_error, e)
            return w
        n.watchers.append(w)
        if backlog:
            w.next_rev = max(e.revision for e in backlog) + 1
            w._outbox.append(backlog)
            w._draining = True
            delay = self.msg_delay(self.cfg.rpc_delay)
            self.loop.call_later(delay, w._drain)
        return w

    # ---- maintenance / status ----------------------------------------------

    async def status(self, node_name: str) -> dict:
        n = await self._enter(node_name)
        return {
            "node": n.name,
            "leader": n.leader_hint,
            "raft-term": n.term,
            "raft-index": n.last_index(),
            "revision": n.store.revision,
            "db-size": n.wal.size + n.snap.size,
            "member-count": len(n.membership),
            "is-leader": n.role == "leader",
        }

    async def compact(self, node_name: str, rev: int,
                      physical: bool = False) -> None:
        n = await self._enter(node_name)
        leader = await self._at_leader(n)
        if rev <= leader.store.compact_revision:
            raise SimError("compacted", f"{rev} already compacted")
        if rev > leader.store.revision:
            raise SimError("compacted", f"{rev} is a future revision")
        await self.propose(leader.name, "compact", rev)
        if physical:
            await self.loop.sleep(10 * MS)

    async def defrag(self, node_name: str) -> None:
        n = await self._enter(node_name)
        await self.loop.sleep(self.loop.rng.randint(50 * MS, 200 * MS))
        n.log_line("defragmented")

    # ---- membership ---------------------------------------------------------

    async def member_list(self, node_name: str) -> list[dict]:
        """Member maps with etcd-style ids and URLs (client.clj:571-613;
        URL scheme peer 2380 / client 2379 per support.clj:12-25)."""
        n = await self._enter(node_name)
        return [{"id": member_id(m), "name": m,
                 "peer-urls": [f"http://{m}:2380"],
                 "client-urls": [f"http://{m}:2379"]}
                for m in n.membership]

    async def member_add(self, via_node: str, new_name: str) -> None:
        n = await self._enter(via_node)
        leader = await self._at_leader(n)
        if new_name in leader.membership:
            raise SimError("duplicate-key", new_name)
        await self.propose(leader.name, "member_add", new_name)

    async def member_remove(self, via_node: str, name: str) -> None:
        n = await self._enter(via_node)
        leader = await self._at_leader(n)
        if name not in leader.membership:
            raise SimError("member-not-found", name)
        await self.propose(leader.name, "member_remove", name)

    # ---- fault API (driven by the nemesis / db layers) ----------------------

    def kill_node(self, name: str, lose_unfsynced: bool = False) -> None:
        n = self.nodes[name]
        if not n.alive:
            return
        n.alive = False
        n.paused = False
        n.role = "follower"
        n.log_line("received signal; shutting down (killed)")
        self._fail_waiters(n, SimError("unavailable", "node killed"))
        for w in list(n.watchers):
            w.cancel(SimError("unavailable", "node killed"))
        if lose_unfsynced or (self.cfg.lazyfs and self.cfg.unsafe_no_fsync):
            n.wal.lose_unfsynced()
            n.snap.lose_unfsynced()
        if n.resume_event is not None:
            n.resume_event.set()
            n.resume_event = None

    def start_node(self, name: str, fresh: bool = False,
                   initial_membership: Optional[list] = None) -> None:
        """(Re)start a node, recovering from its durable files.

        Raises SimError("corrupt") and logs a panic if the WAL or snapshot
        bytes are damaged in a committed region.
        """
        n = self.nodes.get(name)
        if n is None:
            n = Node(name, self,
                     initial_membership or self.initial_names)
            self.nodes[name] = n
        if n.alive:
            return
        if fresh:
            n.wal.clear()
            n.snap.clear()
            n.log = []
            n.log_start = 1
            n.snap_index = n.snap_term = 0
            n.store = Store()
            n.store_applied_index = 0
            n.commit_index = 0
            n.term = 0
            n.membership = list(initial_membership or self.initial_names)
            n.leases = {}
            n.fp_ledger = {}
        else:
            self._recover(n)
        n.alive = True
        n.paused = False
        n.removed = name not in n.membership
        n.role = "follower"
        if fresh:
            n.voted_for = None   # non-fresh restarts keep HardState vote
        n.leader_hint = None
        n.waiters = {}
        n.watchers = []
        n.applied_since_snap = 0
        n.reset_election_deadline()
        n.log_line("etcd server started")

    def _recover(self, n: Node) -> None:
        # ledger restarts with the replay: re-applied entries re-record
        # the same fingerprints at the same indexes (deterministic apply),
        # while a silently-damaged snapshot diverges and gets caught
        n.fp_ledger = {}
        # snapshot
        snap_items, snap_err = n.snap.read()
        if snap_err == "crc-mismatch":
            n.log_line("panic: snap: crc mismatch, cannot load snapshot")
            raise SimError("corrupt", f"{n.name} snapshot corrupt")
        if snap_items:
            idx, term, store, membership, leases = snap_items[0]
            n.store = store.clone()
            n.membership = list(membership)
            n.leases = dict(leases)
            n.snap_index, n.snap_term = idx, term
            n.store_applied_index = idx
            n.log_start = idx + 1
        else:
            n.store = Store()
            n.store_applied_index = 0
            n.snap_index = n.snap_term = 0
            n.log_start = 1
            n.membership = list(self.initial_names)
            n.leases = {}
        # wal
        items, err = n.wal.read()
        if err == "crc-mismatch":
            n.log_line("panic: walpb: crc mismatch")
            raise SimError("corrupt", f"{n.name} WAL corrupt")
        # torn-record at the tail is tolerated (mid-write crash)
        n.log = [LogEntry(i, t, k, p) for (i, t, k, p) in items
                 if i >= n.log_start]
        n.wal_rewrite(n.log)
        # HardState: etcd persists (term, vote) in its WAL and fsyncs it
        # before answering RPCs, so a restarted voter can never re-grant
        # its vote in the same term (raft election safety). We model the
        # hard state as surviving in the Node object across kill/restart
        # (n.term / n.voted_for are simply not cleared); the log-derived
        # term below is only a floor for nodes whose object predates the
        # campaign.
        n.term = max([n.term, n.snap_term] + [e.term for e in n.log])
        # conservative: nothing beyond the snapshot is known committed;
        # the leader's replication will re-advance commit_index.
        n.commit_index = n.snap_index

    def pause_node(self, name: str) -> None:
        n = self.nodes[name]
        if n.alive:
            n.paused = True
            # NOTE: real etcd logs nothing while stopped (the process is
            # frozen); keep the sim marker free of SIG[A-Z]+ so the
            # crash-pattern checker (etcd.clj:134-140) can't false-match
            n.log_line("paused (stop signal)")

    def resume_node(self, name: str) -> None:
        n = self.nodes[name]
        n.paused = False
        n.log_line("resumed (cont signal)")
        if n.resume_event is not None:
            n.resume_event.set()
            n.resume_event = None
        n.reset_election_deadline()

    def partition(self, groups: list[list[str]]) -> None:
        """Partition nodes into isolated groups."""
        self.blocked_pairs = set()
        group_of = {}
        for gi, g in enumerate(groups):
            for name in g:
                group_of[name] = gi
        names = list(self.nodes)
        for i, a in enumerate(names):
            for b in names[i + 1:]:
                if group_of.get(a) != group_of.get(b):
                    self.blocked_pairs.add(frozenset((a, b)))

    def partition_pairs(self, pairs) -> None:
        """Install an explicit blocked set: frozensets block both
        directions, ordered (src, dst) tuples block only src -> dst
        (asymmetric partitions; same encoding as net/plane.py)."""
        self.blocked_pairs = set(pairs)

    def heal_partition(self) -> None:
        self.blocked_pairs = set()

    def set_latency(self, delta_ms: float, jitter_ms: float = 0) -> None:
        """Inject delta + U(0, jitter) extra delay on every message
        leg (the sim backend of the latency nemesis package)."""
        lo = int(delta_ms * MS)
        self.net_latency = (lo, lo + int(jitter_ms * MS))

    def clear_latency(self) -> None:
        self.net_latency = None

    def bump_clock(self, name: str, delta_ns: int) -> None:
        self.nodes[name].clock_offset += delta_ns

    def corrupt_file(self, name: str, which: str = "wal",
                     mode: str = "bitflip", probability: float = 1e-4,
                     truncate_bytes: int = 1024) -> None:
        """Damage durable bytes (nemesis.clj:159-198). Materializes the
        file's framed CRC bytes (BYTES mode) so the damage lands on the
        same byte layout real etcd replay would see."""
        n = self.nodes[name]
        f = n.wal if which == "wal" else n.snap
        f.corrupt(self.loop.rng, mode=mode, probability=probability,
                  truncate_bytes=truncate_bytes)
        n.log_line(f"file corrupted: {which} ({mode})")

    def wipe_node(self, name: str) -> None:
        """Remove all durable state (db.clj:29-36 wipe!); the removal is
        itself durable (the reference checkpoints lazyfs right after the
        rm -rf so wiped files can't come back when unsynced writes are
        later dropped)."""
        n = self.nodes[name]
        n.wal.clear()
        n.snap.clear()

    def checkpoint_node(self, name: str) -> None:
        """lazyfs checkpoint! analog (db.clj:35-36): flush current file
        state to durable, pinning it as the rollback floor for future
        lose-unfsynced kills. Called after setup (db.clj:222-223) so a
        kill never rolls a node back past its initial ready state."""
        self.nodes[name].fsync()

    # ---- invariants ---------------------------------------------------------

    def consistency_report(self) -> dict:
        """Cross-node applied-state fingerprint comparison (the analog of
        etcd's corruption alarm)."""
        fps = {}
        for name, n in self.nodes.items():
            fps[name] = {"applied": n.store_applied_index,
                         "revision": n.store.revision,
                         "fingerprint": n.store.state_fingerprint()}
        return fps

    def check_corruption(self) -> list[dict]:
        """The --corrupt-check monitor pass (db.clj:97-99 enables etcd's
        --experimental-initial-corrupt-check / --corrupt-check-time 1m).

        Applied state at a given raft index is a deterministic function of
        the log prefix, so two nodes whose hashes differ at the SAME
        applied index have definitely diverged — the analog of etcd's
        hashKV peer comparison at a shared revision. Compares both the
        FP_EVERY-multiple ledgers and the live stores of nodes that
        happen to sit at equal applied indexes. New divergences are
        alarm-logged at fatal level on both nodes (so LogFilePattern
        catches them, like etcd's "found data inconsistency with peers"
        fatal) and recorded in self.corruption_alarms.
        """
        new: list[dict] = []
        nodes = sorted(self.nodes)
        live_fp = {a: self.nodes[a].store.state_fingerprint()
                   for a in nodes}
        for i, a in enumerate(nodes):
            na = self.nodes[a]
            for b in nodes[i + 1:]:
                nb = self.nodes[b]
                pairs = [(idx, na.fp_ledger[idx], nb.fp_ledger[idx])
                         for idx in na.fp_ledger.keys() & nb.fp_ledger.keys()]
                if (na.store_applied_index == nb.store_applied_index
                        and na.store_applied_index > 0):
                    pairs.append((na.store_applied_index,
                                  live_fp[a], live_fp[b]))
                for idx, fa, fb in pairs:
                    if fa == fb:
                        continue
                    key = (idx, a, b)
                    if key in self._alarm_keys:
                        continue
                    self._alarm_keys.add(key)
                    alarm = {"index": idx, "nodes": [a, b],
                             "fingerprints": [fa, fb],
                             "time": self.loop.now / SECOND}
                    new.append(alarm)
                    for n in (na, nb):
                        n.etcd_log.append(
                            f'{{"ts":{self.loop.now / SECOND:.3f},'
                            f'"level":"fatal","msg":"checkCorrupt: found '
                            f'data inconsistency with peers","index":{idx},'
                            f'"peers":["{a}","{b}"]}}')
        self.corruption_alarms.extend(new)
        return new
