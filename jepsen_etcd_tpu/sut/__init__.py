from .errors import SimError, ERROR_TYPES
from .store import Store, Txn, cmp, get_op, put_op, del_op, range_op, Event
from .cluster import Cluster, ClusterConfig

__all__ = [
    "SimError", "ERROR_TYPES", "Store", "Txn", "cmp", "get_op", "put_op",
    "del_op", "range_op", "Event", "Cluster", "ClusterConfig",
]
