"""etcd v3 gRPC-JSON gateway server over the simulated MVCC store.

Two jobs:
- the hermetic test double for the real-etcd client adapter
  (client/etcd_http.py): the adapter speaks the same bytes to this
  server as to a live etcd, so its wire encoding (base64 keys/values,
  compare targets, txn branches, chunked watch streams) is exercised
  end-to-end without an etcd binary;
- a live etcd-wire KV endpoint backed by the simulated MVCC store
  (`python -m jepsen_etcd_tpu gateway`) — real etcd tooling can talk
  to the simulated store interactively.

Single-node semantics only (one Store, total order via a lock): the
fault surface of the real adapter is the real cluster's, not this
gateway's.
"""

from __future__ import annotations

import base64
import json
import threading
import time
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from .errors import SimError
from .store import Store, Txn


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode("ascii")


def _unkey(s: str) -> str:
    return base64.b64decode(s).decode("utf-8")


def _unval(s: str) -> Any:
    raw = base64.b64decode(s)
    try:
        return json.loads(raw)
    except ValueError:
        return raw.decode("utf-8", "replace")


def member_id_for_peer_urls(peer_urls) -> int:
    """Stable member id from peer URLs (stand-in for etcd's
    hash-of-peer-URLs+cluster-name id derivation): the same member gets
    the same id whether it is computed by a gateway handling MemberAdd
    or by the fake binary parsing --initial-cluster."""
    blob = ",".join(sorted(peer_urls)).encode("utf-8")
    return zlib.crc32(blob) or 1  # 0 is "no leader" on the wire


_TARGET_FIELD = {"VALUE": ("value", "value"),
                 "VERSION": ("version", "version"),
                 "MOD": ("mod_revision", "mod_revision"),
                 "CREATE": ("create_revision", "create_revision")}
_RESULT_OP = {"EQUAL": "=", "LESS": "<", "GREATER": ">"}


class GatewayState:
    def __init__(self, name: str = "gw0", member_id: int = 1,
                 members: Optional[dict[int, dict]] = None):
        self.store = Store()
        self.lock = threading.Lock()
        self.leases: dict[int, int] = {}  # id -> ttl seconds
        self.next_lease = 0x1000
        # cluster surface: which member this gateway claims to be, and
        # its view of the membership ({id: {"name", "peerURLs",
        # "clientURLs"}}). Defaults preserve the original single-member
        # gateway; the fake-etcd harness passes the full roster so the
        # member list / add / remove API behaves like a real node's.
        self.name = name
        self.member_id = member_id
        self.members: dict[int, dict] = members if members is not None else {
            member_id: {"name": name,
                        "peerURLs": ["http://localhost:0"],
                        "clientURLs": []}}
        # quorum surface: fake-etcd installs a callable reporting
        # whether this node currently sees a roster majority (peer
        # probes, db/fake_etcd.py). None = single-node / always-quorate.
        self.quorum_check = None

    def leader_id(self) -> int:
        # deterministic single leader across every node's view: the
        # lowest member id (fake nodes share no raft; min() agrees).
        # A node cut off from the roster majority has no leader — the
        # wire shape real etcd gives a partitioned minority.
        if self.quorum_check is not None and not self.quorum_check():
            return 0
        return min(self.members) if self.members else 0

    def member_wire(self, mid: int) -> dict:
        m = self.members[mid]
        return {"ID": str(mid), "name": m.get("name", ""),
                "peerURLs": list(m.get("peerURLs", ())),
                "clientURLs": list(m.get("clientURLs", ()))}

    def kv_wire(self, kv: dict) -> dict:
        return {
            "key": _b64(kv["key"].encode("utf-8")),
            "value": _b64(json.dumps(kv["value"]).encode("utf-8")),
            "version": str(kv["version"]),
            "create_revision": str(kv["create-revision"]),
            "mod_revision": str(kv["mod-revision"]),
            "lease": str(kv.get("lease", 0)),
        }


#: paths that need a quorum (writes, linearizable machinery): a real
#: etcd in a partitioned minority fails these with "no leader".
#: Serializable ranges, status, watches, member/list, and lease
#: keepalive stay served from local state, like real etcd.
QUORUM_PATHS = frozenset({
    "/v3/kv/txn", "/v3/kv/compaction",
    "/v3/lease/grant", "/v3/lease/revoke",
    "/v3/lock/lock", "/v3/lock/unlock",
    "/v3/cluster/member/add", "/v3/cluster/member/remove",
})


class _Handler(BaseHTTPRequestHandler):
    state: GatewayState = None  # set by serve()

    def log_message(self, *a):  # quiet
        pass

    def _json(self, obj: dict, code: int = 200) -> None:
        body = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, grpc_code: int, msg: str) -> None:
        self._json({"error": msg, "code": grpc_code, "message": msg},
                   code=code)

    def do_POST(self):  # noqa: N802 (stdlib naming)
        n = int(self.headers.get("Content-Length", 0))
        try:
            body = json.loads(self.rfile.read(n) or b"{}")
        except ValueError:
            return self._error(400, 3, "invalid json")
        st = self.state
        path = self.path
        # body may be any JSON value here (fuzzed frames send lists /
        # null); non-dict bodies fail per-path validation below
        needs_quorum = path in QUORUM_PATHS or (
            path == "/v3/kv/range" and not (
                isinstance(body, dict) and body.get("serializable")))
        if needs_quorum and st.quorum_check is not None \
                and not st.quorum_check():
            # same grpc code (14, unavailable) + message real etcd
            # emits, so client/etcd_http.py classifies identically
            return self._error(503, 14, "etcdserver: no leader")
        try:
            if path == "/v3/kv/range":
                # full Range semantics: optional range_end (half-open
                # interval; "\0" = from key onward) and limit, so real
                # etcd tooling (etcdctl get --prefix) gets correct
                # results. serializable is accepted and identical here:
                # a single-node gateway has no stale followers.
                key = _unkey(body["key"])
                range_end = _unkey(body["range_end"]) \
                    if body.get("range_end") else None
                limit = int(body.get("limit", 0))
                with st.lock:
                    kvs = st.store.range_interval(key, range_end)
                    rev = st.store.revision
                more = bool(limit) and len(kvs) > limit
                count = len(kvs)
                if limit:
                    kvs = kvs[:limit]
                return self._json({
                    "header": {"revision": str(rev)},
                    "kvs": [st.kv_wire(kv) for kv in kvs],
                    "more": more,
                    "count": str(count)})
            if path == "/v3/kv/txn":
                return self._txn(body)
            if path == "/v3/kv/compaction":
                with st.lock:
                    rev = int(body.get("revision", 0))
                    if rev <= st.store.compact_revision:
                        return self._error(
                            400, 11,
                            "etcdserver: mvcc: required revision has "
                            "been compacted")
                    st.store.compact(rev)
                    return self._json(
                        {"header": {"revision": str(st.store.revision)}})
            if path == "/v3/lease/grant":
                with st.lock:
                    st.next_lease += 1
                    lid = st.next_lease
                    st.leases[lid] = int(body.get("TTL", 1))
                return self._json({"ID": str(lid),
                                   "TTL": str(st.leases[lid])})
            if path == "/v3/lease/revoke":
                return self._lease_revoke(int(body["ID"]))
            if path == "/v3/lease/keepalive":
                lid = int(body["ID"])
                with st.lock:
                    ttl = st.leases.get(lid, 0)
                return self._json({"result": {"ID": str(lid),
                                              "TTL": str(ttl)}})
            if path == "/v3/lock/lock":
                return self._lock(body)
            if path == "/v3/lock/unlock":
                return self._unlock(body)
            if path == "/v3/cluster/member/list":
                with st.lock:
                    members = [st.member_wire(mid)
                               for mid in sorted(st.members)]
                # a default single-member gateway advertises its own
                # address (original behaviour); rosters injected by the
                # harness carry real client URLs already
                for m in members:
                    if not m["clientURLs"]:
                        m["clientURLs"] = [
                            f"http://{self.headers.get('Host')}"]
                return self._json({"members": members})
            if path == "/v3/cluster/member/add":
                return self._member_add(body)
            if path == "/v3/cluster/member/remove":
                return self._member_remove(body)
            if path == "/v3/maintenance/status":
                with st.lock:
                    rev = st.store.revision
                    leader = st.leader_id()
                    mid = st.member_id
                return self._json({
                    "header": {"revision": str(rev),
                               "member_id": str(mid)},
                    "leader": str(leader), "raftTerm": "2",
                    "raftIndex": str(rev),
                    "version": "3.5.6-sim-gateway", "dbSize": "0"})
            if path == "/v3/maintenance/defragment":
                return self._json({"header": {}})
            if path == "/v3/watch":
                return self._watch(body)
            return self._error(404, 12, f"unknown path {path}")
        except KeyError as e:
            return self._error(400, 3, f"missing field {e}")
        except (ValueError, TypeError) as e:
            return self._error(400, 3, f"malformed request: {e}")
        except Exception as e:  # store-side errors (e.g. compaction)
            msg = str(e)
            code = 11 if "compact" in msg.lower() else 13
            return self._error(400, code, msg)

    # -- kv txn --------------------------------------------------------------

    def _txn(self, body: dict) -> None:
        st = self.state
        cmps = []
        for c in body.get("compare", []):
            tgt = c.get("target", "VALUE")
            field, store_target = _TARGET_FIELD[tgt]
            operand = c.get(field)
            if tgt == "VALUE":
                operand = _unval(operand) if operand is not None else None
            else:
                operand = int(operand or 0)
            cmps.append((_RESULT_OP[c.get("result", "EQUAL")],
                         _unkey(c["key"]), store_target, operand))

        def branch(ops):
            out = []
            for o in ops:
                if "request_range" in o:
                    out.append(("get", _unkey(o["request_range"]["key"])))
                elif "request_put" in o:
                    p = o["request_put"]
                    out.append(("put", _unkey(p["key"]),
                                _unval(p["value"]),
                                int(p.get("lease", 0))))
                elif "request_delete_range" in o:
                    out.append(("delete",
                                _unkey(o["request_delete_range"]["key"])))
            return out

        txn = Txn(tuple(cmps), tuple(branch(body.get("success", []))),
                  tuple(branch(body.get("failure", []))))
        with st.lock:
            raw = st.store.apply_txn(txn)
        responses = []
        for r in raw["results"]:
            if r[0] == "get":
                responses.append({"response_range": {
                    "kvs": [st.kv_wire(r[1])] if r[1] else [],
                    "count": "1" if r[1] else "0"}})
            elif r[0] == "put":
                responses.append({"response_put": (
                    {"prev_kv": st.kv_wire(r[1])} if r[1] else {})})
            else:
                responses.append({"response_delete_range":
                                  {"deleted": str(r[1])}})
        self._json({"header": {"revision": str(raw["revision"])},
                    "succeeded": raw["succeeded"],
                    "responses": responses})

    # -- leases / locks ------------------------------------------------------

    def _lease_revoke(self, lid: int) -> None:
        st = self.state
        with st.lock:
            if lid not in st.leases:
                return self._error(
                    400, 5, "etcdserver: requested lease not found")
            del st.leases[lid]
            for key in sorted(st.store.lease_keys.get(lid, ())):
                st.store.apply_txn(Txn((), (("delete", key),), ()))
        self._json({"header": {}})

    def _lock(self, body: dict) -> None:
        st = self.state
        name = _unkey(body["name"])
        lid = int(body.get("lease", 0))
        my_key = f"{name}/{lid:016x}"
        deadline = time.monotonic() + 30
        while True:
            with st.lock:
                if lid not in st.leases:
                    return self._error(
                        400, 5, "etcdserver: requested lease not found")
                holders = st.store.range_prefix(name + "/")
                if not holders or all(h["key"] == my_key
                                      for h in holders):
                    st.store.apply_txn(
                        Txn((), (("put", my_key, lid, lid),), ()))
                    return self._json({
                        "key": _b64(my_key.encode("utf-8")),
                        "header": {"revision": str(st.store.revision)}})
            if time.monotonic() > deadline:
                return self._error(400, 4, "lock wait deadline")
            time.sleep(0.01)

    def _unlock(self, body: dict) -> None:
        st = self.state
        key = _unkey(body["key"])
        with st.lock:
            st.store.apply_txn(Txn((), (("delete", key),), ()))
        self._json({"header": {}})

    # -- cluster membership ---------------------------------------------------

    def _member_add(self, body: dict) -> None:
        st = self.state
        peer_urls = list(body.get("peerURLs") or ())
        if not peer_urls:
            return self._error(400, 3,
                               "etcdserver: peerURL exists or is empty")
        # same derivation as the fake binary (crc32 of sorted peer
        # URLs), so an added member keeps its id once it starts and
        # reports itself via --initial-cluster
        mid = member_id_for_peer_urls(peer_urls)
        with st.lock:
            if mid in st.members:
                return self._error(
                    400, 6, "etcdserver: member ID already exist")
            # like real etcd: an added-but-unstarted member has no name
            st.members[mid] = {"name": "", "peerURLs": peer_urls,
                               "clientURLs": []}
            members = [st.member_wire(m) for m in sorted(st.members)]
            rev = st.store.revision
        return self._json({
            "header": {"revision": str(rev),
                       "member_id": str(st.member_id)},
            "member": {"ID": str(mid), "name": "",
                       "peerURLs": peer_urls, "clientURLs": []},
            "members": members})

    def _member_remove(self, body: dict) -> None:
        st = self.state
        mid = int(body["ID"])
        with st.lock:
            if mid not in st.members:
                return self._error(
                    400, 5, "etcdserver: member not found")
            if len(st.members) == 1:
                return self._error(
                    400, 9,
                    "etcdserver: re-configuration failed due to not "
                    "enough started members")
            del st.members[mid]
            members = [st.member_wire(m) for m in sorted(st.members)]
            rev = st.store.revision
        return self._json({
            "header": {"revision": str(rev),
                       "member_id": str(st.member_id)},
            "members": members})

    # -- watch (chunked stream) ----------------------------------------------

    def _watch(self, body: dict) -> None:
        st = self.state
        start = int(body.get("create_request", {})
                    .get("start_revision", 0))
        key = _unkey(body["create_request"]["key"])
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk(obj: dict) -> None:
            data = (json.dumps(obj) + "\n").encode("utf-8")
            self.wfile.write(f"{len(data):x}\r\n".encode())
            self.wfile.write(data + b"\r\n")
            self.wfile.flush()

        chunk({"result": {"created": True, "header": {}}})
        last = max(0, start - 1)
        deadline = time.monotonic() + 300
        try:
            while time.monotonic() < deadline:
                with st.lock:
                    try:
                        events = [e for e in
                                  st.store.events_since(last + 1)
                                  if e.key == key and e.revision > last]
                    except SimError as e:
                        # compacted past the watch: real etcd cancels
                        # the stream with a WatchResponse carrying
                        # compact_revision so the client can restart
                        # past the horizon (api_reference: watch
                        # cancel semantics); mirror that framing.
                        # Only the store's compaction error — anything
                        # else is a real bug and must propagate, not
                        # masquerade as a compact cancel
                        if e.type != "compacted":
                            raise
                        chunk({"result": {
                            "canceled": True,
                            "cancel_reason":
                                "etcdserver: mvcc: required revision "
                                "has been compacted",
                            "compact_revision": str(
                                getattr(e, "compact_revision", None)
                                or st.store.compact_revision)}})
                        return
                    rev = st.store.revision
                if events:
                    last = max(e.revision for e in events)
                    chunk({"result": {
                        "header": {"revision": str(rev)},
                        "events": [{
                            "type": ("DELETE" if e.type == "delete"
                                     else "PUT"),
                            **({"kv": st.kv_wire(e.kv)} if e.kv else
                               {"kv": {
                                   "key": _b64(e.key.encode()),
                                   "mod_revision": str(e.revision)}}),
                            **({"prev_kv": st.kv_wire(e.prev_kv)}
                               if e.prev_kv else {}),
                        } for e in events]}})
                time.sleep(0.02)
        except (BrokenPipeError, ConnectionResetError):
            pass


def serve(port: int = 0,
          state: Optional[GatewayState] = None,
          ) -> tuple[ThreadingHTTPServer, GatewayState]:
    """Start the gateway on localhost:port (0 = ephemeral); returns
    (server, state). Caller runs server.serve_forever() in a thread and
    shutdown()s it when done. Pass `state` to serve a pre-configured
    cluster surface (the fake-etcd harness injects its roster)."""
    state = state if state is not None else GatewayState()
    handler = type("Handler", (_Handler,), {"state": state})
    srv = ThreadingHTTPServer(("127.0.0.1", port), handler)
    # watch handlers poll between events; never block server_close (or
    # interpreter exit) on them
    srv.daemon_threads = True
    srv.block_on_close = False
    return srv, state
