"""Error taxonomy for the simulated SUT.

Mirrors the reference's remap-errors classification
(``client.clj:279-379``): every failure a client can see carries a ``type``
keyword and a ``definite`` flag. Definite errors mean the op certainly did
not happen (checker may treat as :fail); indefinite means unknown (:info).

The type names below preserve the reference's taxonomy keywords so
workload `with_errors` handling (client/errors.py) matches call-site
behavior one-for-one.
"""

from __future__ import annotations


# type -> definite?   (cf. client.clj lines noted)
ERROR_TYPES: dict[str, bool] = {
    "timeout": False,                    # await timeout, client.clj:244-252
    "unavailable": False,                # gRPC UNAVAILABLE, :298-300
    "leader-changed": False,             # :319-320
    "raft-stopped": True,                # "raft: stopped", :322-323
    "not-leader": True,                  # forwarded to dead leader
    "compacted": True,                   # CompactedException, :287-288
    "key-not-found": True,
    "duplicate-key": True,
    "invalid-auth-token": True,
    "too-many-requests": False,          # etcd server overloaded
    "member-not-found": True,
    "unhealthy-cluster": True,           # add-member safety check
    "request-too-large": True,
    "no-leader": False,                  # no leader reachable (election)
    "lease-not-found": True,
    "not-held": True,                    # unlock of a lock we don't hold
    "closed-client": True,
    "connect-failed": False,             # node down at dial time; jetcd
                                         # retries => indefinite by 5s timeout
    "paused": False,                     # SIGSTOP'd node: hangs -> timeout
    "nonmonotonic-watch": True,          # watch.clj:161-177 definite throw
    "corrupt": True,                     # corruption alarm / refuse to serve
    "task-leak": True,                   # sshj thread-leak analog,
                                         # support.clj:57-72
    "crash-loop": True,                  # local node died repeatedly
                                         # during startup (db/local.py)
    "unsupported": True,                 # fault not available in this
                                         # db mode (db/live, db/local)
}


class SimError(Exception):
    """An error from the simulated cluster, classified per the taxonomy."""

    def __init__(self, type_: str, msg: str = "", definite: bool | None = None):
        super().__init__(f"{type_}: {msg}" if msg else type_)
        if type_ not in ERROR_TYPES and definite is None:
            raise ValueError(f"unknown SimError type {type_!r}")
        self.type = type_
        self.definite = ERROR_TYPES[type_] if definite is None else definite

    def as_error_value(self):
        return [self.type, str(self)]
