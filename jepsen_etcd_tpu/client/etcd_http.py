"""Real-etcd client backend over the etcd v3 gRPC-JSON gateway.

SURVEY §7 step 11 (the optional real-etcd adapter): the client seam
makes this additive — the same ``Client`` surface (base.py) implemented
against a live etcd's HTTP gateway (``/v3/kv/txn`` etc., the JSON face
of the gRPC API jetcd speaks in the reference, client.clj:14-68)
instead of the simulated cluster. Runs on a ``WallLoop``
(runner/wall.py): every request is blocking I/O on its thread pool,
re-entering the loop via call_soon_threadsafe.

Values are JSON-encoded into etcd byte values (the role jepsen.codec
plays in the reference, client.clj:80-101); keys are UTF-8. Errors map
into the same taxonomy keywords as the simulated backend
(sut/errors.py), so ``with_errors`` classification — and therefore
history semantics — are identical across sim and real runs.

Hermetic tests drive this adapter against ``sut/http_gateway.py`` — the
same wire format served from the simulated MVCC store — so the adapter
is exercised end-to-end without a real etcd; pointed at a real
cluster's client URL it speaks the same protocol.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Callable, Optional

import urllib.error
import urllib.request

from ..runner.sim import current_loop, wait_for, SECOND
from ..sut.errors import SimError
from ..sut.store import Txn
from .errors import remap_etcd_message
from .base import Client, TIMEOUT, txn_result

_TARGETS = {"value": ("VALUE", "value"),
            "version": ("VERSION", "version"),
            "mod_revision": ("MOD", "mod_revision"),
            "create_revision": ("CREATE", "create_revision")}
_RESULTS = {"=": "EQUAL", "<": "LESS", ">": "GREATER"}

# gRPC status code -> taxonomy keyword (definiteness comes from
# sut/errors.ERROR_TYPES) — the code of the gRPC error jetcd would have
# seen (client.clj:279-379). Message remaps take precedence: etcd packs
# specific conditions (lease-not-found, raft-stopped, leader-changed)
# under generic codes (5/14).
_GRPC_CODES = {
    4: "timeout",            # DEADLINE_EXCEEDED
    5: "key-not-found",      # NOT_FOUND
    6: "duplicate-key",      # ALREADY_EXISTS
    8: "too-many-requests",
    11: "compacted",         # OUT_OF_RANGE: compacted revision
    14: "unavailable",       # UNAVAILABLE
    16: "invalid-auth-token",
}


def _b64(s: bytes) -> str:
    return base64.b64encode(s).decode("ascii")


def _key64(k: str) -> str:
    return _b64(k.encode("utf-8"))


def _val64(v: Any) -> str:
    return _b64(json.dumps(v).encode("utf-8"))


def _unkey(s: str) -> str:
    return base64.b64decode(s).decode("utf-8")


def _unval(s: Optional[str]) -> Any:
    if s is None:
        return None
    raw = base64.b64decode(s)
    try:
        return json.loads(raw)
    except ValueError:
        return raw.decode("utf-8", "replace")  # non-codec writer


def _kv_from_wire(kv: dict) -> dict:
    return {
        "key": _unkey(kv["key"]),
        "value": _unval(kv.get("value")),
        "version": int(kv.get("version", 0)),
        "create-revision": int(kv.get("create_revision", 0)),
        "mod-revision": int(kv.get("mod_revision", 0)),
        "lease": int(kv.get("lease", 0)),
    }


def _classify_http_error(e: BaseException) -> SimError:
    if isinstance(e, urllib.error.HTTPError):
        try:
            body = json.loads(e.read().decode("utf-8", "replace"))
        except Exception:
            body = {}
        code = int(body.get("code", -1))
        msg = body.get("message") or body.get("error") or str(e)
        # message remaps FIRST (client.clj:302-353), shared with the
        # native-gRPC adapter so the same server fault classifies
        # identically per --client-type
        remapped = remap_etcd_message(msg)
        if remapped is not None:
            return remapped
        if code in _GRPC_CODES:
            return SimError(_GRPC_CODES[code], msg)
        return SimError("unavailable", msg, definite=False)
    if isinstance(e, urllib.error.URLError):
        return SimError("connect-failed", str(e.reason))
    return SimError("unavailable", repr(e), definite=False)


class HttpEtcdClient(Client):
    """The real-etcd backend; same public surface as the sim-backed
    Client, minus the sim-only fault hooks."""

    def __init__(self, endpoint: str):
        # deliberately no super().__init__: there is no simulated cluster
        self.endpoint = endpoint.rstrip("/")
        self.node = self.endpoint
        self.cluster = None
        self.open = True

    # ---- plumbing ----------------------------------------------------------

    def _post_sync(self, path: str, body: dict,
                   timeout_s: float) -> dict:
        req = urllib.request.Request(
            self.endpoint + path,
            data=json.dumps(body).encode("utf-8"),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))

    async def _post(self, path: str, body: dict,
                    timeout: int = TIMEOUT) -> dict:
        if not self.open:
            raise SimError("closed-client", self.endpoint)
        loop = current_loop()
        if not hasattr(loop, "run_in_thread"):
            raise RuntimeError("HttpEtcdClient needs a WallLoop "
                               "(runner/wall.py): real I/O cannot run "
                               "on the virtual-time SimLoop")
        fut = loop.run_in_thread(self._post_sync, path, body,
                                 max(0.1, timeout / SECOND))
        try:
            return await wait_for(fut, timeout)
        except (SimError, TimeoutError):
            raise
        except BaseException as e:
            raise _classify_http_error(e) from e

    # ---- txn seam ----------------------------------------------------------

    async def _txn_rpc(self, txn: Txn) -> dict:
        body: dict = {"compare": [], "success": [], "failure": []}
        for op, key, target, operand in txn.cmps:
            tgt, field = _TARGETS[target]
            c = {"key": _key64(key), "target": tgt,
                 "result": _RESULTS[op]}
            c[field] = _val64(operand) if target == "value" \
                else int(operand)
            body["compare"].append(c)
        for branch, ops in (("success", txn.then_ops),
                            ("failure", txn.else_ops)):
            for o in ops:
                if o[0] == "get":
                    body[branch].append(
                        {"request_range": {"key": _key64(o[1])}})
                elif o[0] == "put":
                    body[branch].append({"request_put": {
                        "key": _key64(o[1]), "value": _val64(o[2]),
                        "lease": int(o[3]) if len(o) > 3 else 0,
                        "prev_kv": True}})
                else:
                    body[branch].append({"request_delete_range": {
                        "key": _key64(o[1]), "prev_kv": True}})
        raw = await self._post("/v3/kv/txn", body)
        results = []
        applied = txn.then_ops if raw.get("succeeded") else txn.else_ops
        for o, r in zip(applied, raw.get("responses", [])):
            if o[0] == "get":
                kvs = r.get("response_range", {}).get("kvs", [])
                results.append(
                    ("get", _kv_from_wire(kvs[0]) if kvs else None))
            elif o[0] == "put":
                prev = r.get("response_put", {}).get("prev_kv")
                results.append(
                    ("put", _kv_from_wire(prev) if prev else None))
            else:
                results.append(("delete", int(
                    r.get("response_delete_range", {}).get("deleted",
                                                           0))))
        return {"succeeded": bool(raw.get("succeeded")),
                "results": results,
                "revision": int(raw.get("header", {}).get("revision", 0))}

    # ---- KV ----------------------------------------------------------------

    async def get(self, k: str, serializable: bool = False
                  ) -> Optional[dict]:
        raw = await self._post("/v3/kv/range", {
            "key": _key64(k), "limit": 1, "serializable": serializable})
        kvs = raw.get("kvs", [])
        return _kv_from_wire(kvs[0]) if kvs else None

    async def revision(self) -> int:
        raw = await self._post("/v3/kv/range",
                               {"key": _key64("\x00"), "limit": 1})
        return int(raw.get("header", {}).get("revision", 0))

    # ---- leases ------------------------------------------------------------

    async def lease_grant(self, ttl_ns: int) -> int:
        # round UP: truncation would grant a 2.9s lease as TTL=2,
        # expiring earlier than the harness's lease math assumes
        ttl_s = max(1, -(-int(ttl_ns) // SECOND))
        raw = await self._post("/v3/lease/grant", {"TTL": ttl_s})
        return int(raw["ID"])

    async def lease_revoke(self, lease_id: int) -> None:
        await self._post("/v3/lease/revoke", {"ID": int(lease_id)})

    async def lease_keepalive_once(self, lease_id: int) -> int:
        raw = await self._post("/v3/lease/keepalive",
                               {"ID": int(lease_id)})
        res = raw.get("result", raw)
        ttl = int(res.get("TTL", 0))
        if ttl <= 0:
            raise SimError("lease-not-found", f"lease {lease_id:x}")
        return ttl * SECOND

    # ---- locks -------------------------------------------------------------

    async def acquire_lock(self, name: str, lease_id: int,
                           timeout: int = TIMEOUT) -> str:
        raw = await self._post("/v3/lock/lock",
                               {"name": _key64(name),
                                "lease": int(lease_id)}, timeout)
        return _unkey(raw["key"])

    async def release_lock(self, lock_key: str) -> None:
        await self._post("/v3/lock/unlock", {"key": _key64(lock_key)})

    # ---- watch -------------------------------------------------------------

    def watch(self, k: str, from_revision: int,
              on_events: Callable, on_error: Callable):
        """Streaming watch over the gateway (chunked JSON lines). Events
        arrive as sut.store.Event-shaped objects, matching the sim."""
        import threading

        from ..sut.store import Event
        loop = current_loop()
        stop = {"flag": False, "resp": None}

        def _shutdown_socket(resp) -> None:
            # resp.close() would deadlock on the buffered-reader lock a
            # blocked readline holds; shutting down the RAW socket
            # unblocks it immediately
            try:
                sock = resp.fp.raw._sock if resp is not None else None
                if sock is not None:
                    import socket as _socket
                    sock.shutdown(_socket.SHUT_RDWR)
            except Exception:
                pass  # already closed / implementation detail moved

        def reader():
            body = json.dumps({"create_request": {
                "key": _key64(k),
                "start_revision": int(from_revision)}}).encode("utf-8")
            req = urllib.request.Request(
                self.endpoint + "/v3/watch", data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=3600) as resp:
                    stop["resp"] = resp
                    if stop["flag"]:
                        # cancel() ran while the connection was still
                        # being established (before resp existed): its
                        # socket shutdown missed, so do it ourselves or
                        # this daemon thread pins the connection until
                        # the 1h read timeout
                        _shutdown_socket(resp)
                        return
                    for line in resp:
                        if stop["flag"]:
                            return
                        msg = json.loads(line.decode("utf-8"))
                        res = msg.get("result", {})
                        if res.get("canceled"):
                            # servers also cancel watches for
                            # NON-compaction reasons (failed create,
                            # shutdown); classifying those as
                            # "compacted" would let the checker excuse
                            # real missing events as a phantom gap —
                            # gate on the compaction evidence
                            reason = res.get("cancel_reason", "canceled")
                            try:
                                cr = int(res.get("compact_revision"))
                            except (TypeError, ValueError):
                                # a non-numeric compact_revision (a
                                # gateway str() fallback can yield
                                # "None") must not escape as a generic
                                # error and lose the compaction framing
                                cr = 0
                            if cr > 0 or "compacted" in reason.lower():
                                # compaction cancel: carry the true
                                # horizon so the workload restarts
                                # there instead of at max-observed
                                # revision (which can overstate the
                                # unobservable gap)
                                err = SimError("compacted", reason)
                                if cr > 0:
                                    err.compact_revision = cr
                            else:
                                err = SimError("unavailable",
                                               f"watch canceled: "
                                               f"{reason}",
                                               definite=False)
                            if not stop["flag"]:
                                loop.call_soon_threadsafe(on_error, err)
                            return
                        evs = []
                        for e in res.get("events", []):
                            kv = _kv_from_wire(e["kv"]) if "kv" in e \
                                else None
                            prev = _kv_from_wire(e["prev_kv"]) \
                                if "prev_kv" in e else None
                            etype = ("delete" if e.get("type") == "DELETE"
                                     else "put")
                            rev = (kv or prev or {}).get(
                                "mod-revision",
                                int(res.get("header", {}).get(
                                    "revision", 0)))
                            evs.append(Event(type=etype,
                                             key=(kv or prev or
                                                  {"key": k})["key"],
                                             kv=kv, prev_kv=prev,
                                             revision=rev))
                        if evs and not stop["flag"]:
                            loop.call_soon_threadsafe(on_events, evs)
                    # stream EOF with neither a cancel frame nor a
                    # local cancel: the server went away mid-stream
                    # (killed node). Surface it as an indefinite outage
                    # so the consumer re-establishes the watch instead
                    # of waiting on a dead stream forever (same fix as
                    # the native-gRPC reader)
                    if not stop["flag"]:
                        loop.call_soon_threadsafe(on_error, SimError(
                            "unavailable",
                            "watch stream ended without cancel (server "
                            "went away)", definite=False))
            except BaseException as e:
                if not stop["flag"]:
                    loop.call_soon_threadsafe(
                        on_error, _classify_http_error(e))

        # a dedicated daemon thread, NOT the loop's pool: the stream
        # blocks in readline between events, which would pin a pool
        # worker and block interpreter exit on the atexit join
        threading.Thread(target=reader, daemon=True,
                         name=f"watch-{k}").start()

        class _Cancel:
            def cancel(self_inner):
                # order matters for the connect race: set the flag FIRST
                # so a reader that assigns stop['resp'] after this call
                # sees it and shuts its own socket down (see reader())
                stop["flag"] = True
                _shutdown_socket(stop.get("resp"))

        return _Cancel()

    # ---- membership / maintenance -----------------------------------------

    async def member_list(self) -> list[dict]:
        raw = await self._post("/v3/cluster/member/list", {})
        return [{"id": int(m["ID"]), "name": m.get("name", ""),
                 "peer-urls": m.get("peerURLs", []),
                 "client-urls": m.get("clientURLs", [])}
                for m in raw.get("members", [])]

    async def add_member(self, name: str) -> None:
        raise SimError("unavailable",
                       "member add needs peer URLs: use "
                       "member_add_urls (the local control plane, "
                       "db/local.py, supplies them)", definite=True)

    async def member_add_urls(self, peer_urls: list[str],
                              is_learner: bool = False) -> dict:
        """Real member add (MemberAdd, client.clj:615-622 analog): the
        caller — the local control plane — knows the new node's peer
        URLs before it starts. Returns the new member map."""
        raw = await self._post("/v3/cluster/member/add",
                               {"peerURLs": list(peer_urls),
                                "isLearner": bool(is_learner)})
        m = raw.get("member", {})
        return {"id": int(m.get("ID", 0)), "name": m.get("name", ""),
                "peer-urls": list(m.get("peerURLs", ()))}

    async def remove_member(self, name: str) -> None:
        for m in await self.member_list():
            if m["name"] == name:
                await self.remove_member_by_id(m["id"])
                return
        raise SimError("member-not-found", name)

    async def remove_member_by_id(self, member_id: int) -> None:
        await self._post("/v3/cluster/member/remove",
                         {"ID": int(member_id)})

    async def status(self) -> dict:
        raw = await self._post("/v3/maintenance/status", {})
        return {"leader": int(raw.get("leader", 0)) or None,
                "version": raw.get("version"),
                "db-size": int(raw.get("dbSize", 0)),
                "raft-term": int(raw.get("raftTerm", 0)),
                "raft-index": int(raw.get("raftIndex", 0)),
                "header": raw.get("header", {})}

    async def compact(self, rev: int, physical: bool = True) -> None:
        await self._post("/v3/kv/compaction",
                         {"revision": int(rev), "physical": physical})

    async def defrag(self) -> None:
        await self._post("/v3/maintenance/defragment", {})

    # await_node_ready: the base Client implementation works unchanged
    # through the overridden status()
