from . import txn
from .errors import with_errors, client_error
from .base import Client, TIMEOUT
from .direct import DirectClient
from .etcdctl import EtcdctlClient

__all__ = ["txn", "with_errors", "client_error", "Client", "TIMEOUT",
           "DirectClient", "EtcdctlClient"]


def client(test, node: str):
    """Construct a client for a node, dispatching on test['client_type']
    (mirrors the reference constructor dispatch, client.clj:210-222)."""
    ctype = (test.get("client_type") or "direct") if isinstance(test, dict) \
        else "direct"
    if ctype == "http":
        # live-etcd mode (etcd.clj:246-257 drives a real cluster): the
        # node IS its endpoint URL
        from .etcd_http import HttpEtcdClient
        return HttpEtcdClient(node)
    if ctype == "grpc":
        # live-etcd mode over native gRPC — the reference's wire
        # protocol (jetcd, client.clj:14-68)
        from .etcd_grpc import GrpcEtcdClient
        return GrpcEtcdClient(node)
    cluster = test["cluster"]
    if ctype == "direct":
        return DirectClient(cluster, node)
    if ctype == "etcdctl":
        return EtcdctlClient(cluster, node)
    raise ValueError(f"unknown client type {ctype!r}")
