from . import txn
from .errors import with_errors, client_error
from .base import Client, TIMEOUT
from .direct import DirectClient
from .etcdctl import EtcdctlClient

__all__ = ["txn", "with_errors", "client_error", "Client", "TIMEOUT",
           "DirectClient", "EtcdctlClient"]


def client(test, node: str):
    """Construct a client for a node, dispatching on test['client_type']
    (mirrors the reference constructor dispatch, client.clj:210-222)."""
    ctype = (test.get("client_type") or "direct") if isinstance(test, dict) \
        else "direct"
    if ctype in ("http", "grpc"):
        # live-etcd mode (etcd.clj:246-257 drives a real cluster). With
        # the local control plane (--db local) the node is a NAME and
        # the driver owns the name -> client URL mapping; in plain live
        # mode the node IS its endpoint URL
        endpoint = node
        if isinstance(test, dict) and test.get("db_mode") == "local":
            endpoint = test["db"].client_url(node)
        if ctype == "http":
            from .etcd_http import HttpEtcdClient
            c = HttpEtcdClient(endpoint)
        else:
            # native gRPC — the reference's wire protocol (jetcd,
            # client.clj:14-68)
            from .etcd_grpc import GrpcEtcdClient
            c = GrpcEtcdClient(endpoint)
        c.node = node  # histories and per-node stats keyed by name
        return c
    cluster = test["cluster"]
    if ctype == "direct":
        return DirectClient(cluster, node)
    if ctype == "etcdctl":
        return EtcdctlClient(cluster, node)
    raise ValueError(f"unknown client type {ctype!r}")
