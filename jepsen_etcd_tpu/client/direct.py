"""The direct (in-process gRPC analog) client backend."""

from __future__ import annotations

from .base import Client
from ..sut.store import Txn


class DirectClient(Client):
    """Speaks to the simulated cluster natively — the jetcd-analog backend
    (client.clj:723-750 implements the txn seam over jetcd)."""

    async def _txn_rpc(self, txn: Txn) -> dict:
        return await self._call(self.cluster.kv_txn(self.node, txn))
