"""Real-etcd client backend over native gRPC — the reference's actual
wire protocol.

The reference's entire client traffic is gRPC/HTTP2 via jetcd
(project.clj:11, client.clj:14-68; commit path client.clj:723-750).
This adapter closes the one wire-protocol gap the JSON-gateway adapter
(etcd_http.py) left: it speaks etcdserverpb/v3lockpb directly over a
``grpc`` channel, using hand-maintained message classes
(client/proto/etcd_rpc.proto — field numbers mirror etcd's published
rpc.proto, see that file's header) and explicit method paths, so no
grpc_tools codegen is required.

Runs on a ``WallLoop`` like the HTTP adapter: every unary call is
blocking I/O on the loop's thread pool; the watch and lease-keepalive
streams live on dedicated daemon threads. Values are JSON-encoded into
etcd byte values (jepsen.codec's role, client.clj:80-101) — identical
bytes to the HTTP adapter and the etcdctl/direct sim clients, so
histories and checker semantics agree across every client type.

Error taxonomy: gRPC status codes map to the same keywords as
etcd_http._GRPC_CODES, message remaps first (client.clj:302-353 —
etcd hides specific conditions under generic codes). Hermetic tests
drive this adapter against ``sut/grpc_gateway.py`` (the same simulated
MVCC store served over real gRPC); pointed at a real cluster's client
URL it speaks the same protocol as jetcd.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Callable, Optional

from ..runner.sim import current_loop, wait_for, SECOND
from ..sut.errors import SimError
from ..sut.store import Txn
from .base import Client, TIMEOUT
from .errors import remap_etcd_message
from .proto import etcd_rpc_pb2 as pb

_TARGETS = {"value": pb.Compare.VALUE, "version": pb.Compare.VERSION,
            "mod_revision": pb.Compare.MOD,
            "create_revision": pb.Compare.CREATE}
_RESULTS = {"=": pb.Compare.EQUAL, "<": pb.Compare.LESS,
            ">": pb.Compare.GREATER}

#: gRPC StatusCode name -> taxonomy keyword (same table as the JSON
#: gateway adapter, keyed by symbolic name instead of numeric code)
_CODE_NAMES = {
    "DEADLINE_EXCEEDED": "timeout",
    "NOT_FOUND": "key-not-found",
    "ALREADY_EXISTS": "duplicate-key",
    "RESOURCE_EXHAUSTED": "too-many-requests",
    "OUT_OF_RANGE": "compacted",
    "UNAVAILABLE": "unavailable",
    "UNAUTHENTICATED": "invalid-auth-token",
}

#: method path -> (request class, response class); paths are the wire
#: contract (etcd's service/package names), independent of our local
#: proto package name
_METHODS = {
    "range": ("/etcdserverpb.KV/Range", pb.RangeRequest,
              pb.RangeResponse),
    "txn": ("/etcdserverpb.KV/Txn", pb.TxnRequest, pb.TxnResponse),
    "compact": ("/etcdserverpb.KV/Compact", pb.CompactionRequest,
                pb.CompactionResponse),
    "lease_grant": ("/etcdserverpb.Lease/LeaseGrant",
                    pb.LeaseGrantRequest, pb.LeaseGrantResponse),
    "lease_revoke": ("/etcdserverpb.Lease/LeaseRevoke",
                     pb.LeaseRevokeRequest, pb.LeaseRevokeResponse),
    "member_list": ("/etcdserverpb.Cluster/MemberList",
                    pb.MemberListRequest, pb.MemberListResponse),
    "member_add": ("/etcdserverpb.Cluster/MemberAdd",
                   pb.MemberAddRequest, pb.MemberAddResponse),
    "member_remove": ("/etcdserverpb.Cluster/MemberRemove",
                      pb.MemberRemoveRequest, pb.MemberRemoveResponse),
    "status": ("/etcdserverpb.Maintenance/Status", pb.StatusRequest,
               pb.StatusResponse),
    "defragment": ("/etcdserverpb.Maintenance/Defragment",
                   pb.DefragmentRequest, pb.DefragmentResponse),
    "lock": ("/v3lockpb.Lock/Lock", pb.LockRequest, pb.LockResponse),
    "unlock": ("/v3lockpb.Lock/Unlock", pb.UnlockRequest,
               pb.UnlockResponse),
}

WATCH_PATH = "/etcdserverpb.Watch/Watch"
KEEPALIVE_PATH = "/etcdserverpb.Lease/LeaseKeepAlive"


def _val_bytes(v: Any) -> bytes:
    return json.dumps(v).encode("utf-8")


def _unval(b: bytes) -> Any:
    if not b:
        return None
    try:
        return json.loads(b)
    except ValueError:
        return b.decode("utf-8", "replace")  # non-codec writer


def _kv_from_wire(kv: pb.KeyValue) -> dict:
    return {
        "key": kv.key.decode("utf-8"),
        "value": _unval(kv.value),
        "version": kv.version,
        "create-revision": kv.create_revision,
        "mod-revision": kv.mod_revision,
        "lease": kv.lease,
    }


def classify_grpc_error(e: BaseException) -> SimError:
    """RpcError -> taxonomy keyword. Message remaps FIRST
    (client.clj:302-353): etcd packs specific conditions
    (lease-not-found, raft-stopped, leader-changed) under generic
    codes."""
    import grpc

    if isinstance(e, grpc.RpcError):
        code = e.code() if callable(getattr(e, "code", None)) else None
        msg = (e.details() if callable(getattr(e, "details", None))
               else None) or str(e)
        remapped = remap_etcd_message(msg)
        if remapped is not None:
            return remapped
        name = code.name if code is not None else ""
        if name in _CODE_NAMES:
            return SimError(_CODE_NAMES[name], msg)
        if name == "CANCELLED":
            return SimError("closed-client", msg)
        return SimError("unavailable", msg, definite=False)
    return SimError("unavailable", repr(e), definite=False)


def _target(endpoint: str) -> str:
    """A client URL ('http://host:port') or bare 'host:port' -> the
    grpc channel target."""
    for scheme in ("http://", "https://"):
        if endpoint.startswith(scheme):
            return endpoint[len(scheme):].rstrip("/")
    return endpoint.rstrip("/")


class GrpcEtcdClient(Client):
    """The native-gRPC real-etcd backend; same public surface as the
    sim-backed Client, minus the sim-only fault hooks."""

    def __init__(self, endpoint: str):
        # deliberately no super().__init__: there is no simulated cluster
        import grpc

        self.endpoint = endpoint
        self.node = endpoint
        self.cluster = None
        self.open = True
        self._channel = grpc.insecure_channel(_target(endpoint))
        self._calls = {}
        for name, (path, req_cls, resp_cls) in _METHODS.items():
            self._calls[name] = self._channel.unary_unary(
                path, request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString)
        self._watch_call = self._channel.stream_stream(
            WATCH_PATH,
            request_serializer=pb.WatchRequest.SerializeToString,
            response_deserializer=pb.WatchResponse.FromString)
        self._keepalive_call = self._channel.stream_stream(
            KEEPALIVE_PATH,
            request_serializer=pb.LeaseKeepAliveRequest.SerializeToString,
            response_deserializer=pb.LeaseKeepAliveResponse.FromString)

    # ---- plumbing ----------------------------------------------------------

    def _wall_loop(self):
        """The current loop, asserted to be a WallLoop — the one guard
        every real-I/O path (unary, keepalive stream, watch stream)
        shares, so a SimLoop gets this deliberate error instead of a
        bare AttributeError deep in a thread helper."""
        loop = current_loop()
        if not hasattr(loop, "run_in_thread"):
            raise RuntimeError("GrpcEtcdClient needs a WallLoop "
                               "(runner/wall.py): real I/O cannot run "
                               "on the virtual-time SimLoop")
        return loop

    async def _guarded(self, fn, *args, timeout: int = TIMEOUT):
        """Run blocking gRPC I/O on the WallLoop's thread pool with the
        client timeout and taxonomy classification."""
        if not self.open:
            raise SimError("closed-client", self.endpoint)
        loop = self._wall_loop()
        fut = loop.run_in_thread(fn, *args)
        try:
            return await wait_for(fut, timeout)
        except (SimError, TimeoutError):
            raise
        except BaseException as e:
            raise classify_grpc_error(e) from e

    async def _call(self, name: str, req, timeout: int = TIMEOUT):
        return await self._guarded(self._calls[name], req,
                                   max(0.1, timeout / SECOND),
                                   timeout=timeout)

    def close(self) -> None:
        self.open = False
        try:
            self._channel.close()
        except Exception:
            pass

    # ---- txn seam ----------------------------------------------------------

    async def _txn_rpc(self, txn: Txn) -> dict:
        req = pb.TxnRequest()
        for op, key, target, operand in txn.cmps:
            c = req.compare.add()
            c.key = key.encode("utf-8")
            c.target = _TARGETS[target]
            c.result = _RESULTS[op]
            if target == "value":
                c.value = _val_bytes(operand)
            elif target == "version":
                c.version = int(operand)
            elif target == "mod_revision":
                c.mod_revision = int(operand)
            else:
                c.create_revision = int(operand)
        for branch, ops in ((req.success, txn.then_ops),
                            (req.failure, txn.else_ops)):
            for o in ops:
                ro = branch.add()
                if o[0] == "get":
                    ro.request_range.key = o[1].encode("utf-8")
                elif o[0] == "put":
                    ro.request_put.key = o[1].encode("utf-8")
                    ro.request_put.value = _val_bytes(o[2])
                    if len(o) > 3:
                        ro.request_put.lease = int(o[3])
                    ro.request_put.prev_kv = True
                else:
                    ro.request_delete_range.key = o[1].encode("utf-8")
                    ro.request_delete_range.prev_kv = True
        raw = await self._call("txn", req)
        results = []
        applied = txn.then_ops if raw.succeeded else txn.else_ops
        for o, r in zip(applied, raw.responses):
            if o[0] == "get":
                kvs = r.response_range.kvs
                results.append(
                    ("get", _kv_from_wire(kvs[0]) if kvs else None))
            elif o[0] == "put":
                prev = (r.response_put.prev_kv
                        if r.response_put.HasField("prev_kv") else None)
                results.append(
                    ("put", _kv_from_wire(prev) if prev else None))
            else:
                results.append(
                    ("delete", int(r.response_delete_range.deleted)))
        return {"succeeded": bool(raw.succeeded), "results": results,
                "revision": int(raw.header.revision)}

    # ---- KV ----------------------------------------------------------------

    async def get(self, k: str, serializable: bool = False
                  ) -> Optional[dict]:
        raw = await self._call("range", pb.RangeRequest(
            key=k.encode("utf-8"), limit=1, serializable=serializable))
        return _kv_from_wire(raw.kvs[0]) if raw.kvs else None

    async def revision(self) -> int:
        raw = await self._call("range",
                               pb.RangeRequest(key=b"\x00", limit=1))
        return int(raw.header.revision)

    # ---- leases ------------------------------------------------------------

    async def lease_grant(self, ttl_ns: int) -> int:
        # round UP: truncation would grant a 2.9s lease as TTL=2,
        # expiring earlier than the harness's lease math assumes
        ttl_s = max(1, -(-int(ttl_ns) // SECOND))
        raw = await self._call("lease_grant",
                               pb.LeaseGrantRequest(TTL=ttl_s))
        return int(raw.ID)

    async def lease_revoke(self, lease_id: int) -> None:
        await self._call("lease_revoke",
                         pb.LeaseRevokeRequest(ID=int(lease_id)))

    def _keepalive_sync(self, lease_id: int, timeout_s: float) -> int:
        """One round on the LeaseKeepAlive bidi stream (jetcd keeps a
        long-lived stream; one-shot preserves the same wire frames)."""
        call = self._keepalive_call(
            iter([pb.LeaseKeepAliveRequest(ID=int(lease_id))]),
            timeout=timeout_s)
        try:
            resp = next(iter(call))
        finally:
            call.cancel()
        return int(resp.TTL)

    async def lease_keepalive_once(self, lease_id: int) -> int:
        ttl = await self._guarded(self._keepalive_sync, lease_id,
                                  max(0.1, TIMEOUT / SECOND))
        if ttl <= 0:
            raise SimError("lease-not-found", f"lease {lease_id:x}")
        return ttl * SECOND

    # ---- locks -------------------------------------------------------------

    async def acquire_lock(self, name: str, lease_id: int,
                           timeout: int = TIMEOUT) -> str:
        raw = await self._call("lock", pb.LockRequest(
            name=name.encode("utf-8"), lease=int(lease_id)), timeout)
        return raw.key.decode("utf-8")

    async def release_lock(self, lock_key: str) -> None:
        await self._call("unlock", pb.UnlockRequest(
            key=lock_key.encode("utf-8")))

    # ---- watch -------------------------------------------------------------

    def watch(self, k: str, from_revision: int,
              on_events: Callable, on_error: Callable):
        """Streaming watch on the etcdserverpb.Watch bidi stream.
        Events arrive as sut.store.Event-shaped objects, matching the
        sim and the JSON-gateway adapter."""
        from ..sut.store import Event

        loop = self._wall_loop()
        stop = {"flag": False, "call": None}
        started = threading.Event()

        def requests():
            req = pb.WatchRequest()
            req.create_request.key = k.encode("utf-8")
            req.create_request.start_revision = int(from_revision)
            req.create_request.prev_kv = True
            yield req
            started.wait()  # hold the send side open until cancel

        def reader():
            call = None
            try:
                call = self._watch_call(requests(), timeout=3600)
                stop["call"] = call
                if stop["flag"]:
                    return
                for msg in call:
                    if stop["flag"]:
                        return
                    if msg.canceled:
                        # servers also cancel watches for NON-compaction
                        # reasons (failed create, shutdown); gate the
                        # "compacted" classification on the compaction
                        # evidence so real missing events can't hide
                        # behind a phantom gap
                        reason = msg.cancel_reason or "canceled"
                        cr = int(msg.compact_revision)
                        if cr > 0 or "compacted" in reason.lower():
                            err = SimError("compacted", reason)
                            if cr > 0:
                                err.compact_revision = cr
                        else:
                            err = SimError(
                                "unavailable",
                                f"watch canceled: {reason}",
                                definite=False)
                        if not stop["flag"]:
                            loop.call_soon_threadsafe(on_error, err)
                        return
                    evs = []
                    for e in msg.events:
                        kv = (_kv_from_wire(e.kv)
                              if e.HasField("kv") else None)
                        prev = (_kv_from_wire(e.prev_kv)
                                if e.HasField("prev_kv") else None)
                        etype = ("delete" if e.type == pb.Event.DELETE
                                 else "put")
                        rev = (kv or prev or {}).get(
                            "mod-revision", int(msg.header.revision))
                        evs.append(Event(
                            type=etype,
                            key=(kv or prev or {"key": k})["key"],
                            kv=kv, prev_kv=prev, revision=rev))
                    if evs and not stop["flag"]:
                        loop.call_soon_threadsafe(on_events, evs)
                # the stream ended with neither a cancel frame nor a
                # local cancel: the server side went away mid-stream
                # (killed node, closed connection). A silent return here
                # would strand the consumer on a dead watch forever —
                # surface it as an indefinite outage so it re-establishes
                if not stop["flag"]:
                    loop.call_soon_threadsafe(on_error, SimError(
                        "unavailable",
                        "watch stream ended without cancel (server "
                        "went away)", definite=False))
            except BaseException as e:
                if not stop["flag"]:
                    loop.call_soon_threadsafe(
                        on_error, classify_grpc_error(e))
            finally:
                # EVERY exit releases the request generator and the
                # call: a server-initiated end (compaction cancel,
                # error, stream close) must not leave grpc's request-
                # consumer thread parked in started.wait() forever
                started.set()
                if call is not None:
                    try:
                        call.cancel()
                    except Exception:
                        pass

        threading.Thread(target=reader, daemon=True,
                         name=f"watch-{k}").start()

        class _Cancel:
            def cancel(self_inner):
                # flag FIRST: a reader assigning stop['call'] after this
                # call sees it and cancels its own stream (connect race)
                stop["flag"] = True
                started.set()  # release the request generator
                call = stop.get("call")
                if call is not None:
                    try:
                        call.cancel()
                    except Exception:
                        pass

        return _Cancel()

    # ---- membership / maintenance -----------------------------------------

    async def member_list(self) -> list[dict]:
        raw = await self._call("member_list", pb.MemberListRequest())
        return [{"id": int(m.ID), "name": m.name,
                 "peer-urls": list(m.peerURLs),
                 "client-urls": list(m.clientURLs)}
                for m in raw.members]

    async def add_member(self, name: str) -> None:
        raise SimError("unavailable",
                       "member add needs peer URLs: use "
                       "member_add_urls (the local control plane, "
                       "db/local.py, supplies them)", definite=True)

    async def member_add_urls(self, peer_urls: list[str],
                              is_learner: bool = False) -> dict:
        """Real member add (MemberAdd, client.clj:615-622 analog): the
        caller — the local control plane — knows the new node's peer
        URLs before it starts. Returns the new member map."""
        raw = await self._call("member_add", pb.MemberAddRequest(
            peerURLs=list(peer_urls), isLearner=bool(is_learner)))
        return {"id": int(raw.member.ID), "name": raw.member.name,
                "peer-urls": list(raw.member.peerURLs)}

    async def remove_member(self, name: str) -> None:
        for m in await self.member_list():
            if m["name"] == name:
                await self.remove_member_by_id(m["id"])
                return
        raise SimError("member-not-found", name)

    async def remove_member_by_id(self, member_id: int) -> None:
        await self._call("member_remove",
                         pb.MemberRemoveRequest(ID=int(member_id)))

    async def status(self) -> dict:
        raw = await self._call("status", pb.StatusRequest())
        return {"leader": int(raw.leader) or None,
                "version": raw.version,
                "db-size": int(raw.dbSize),
                "raft-term": int(raw.raftTerm),
                "raft-index": int(raw.raftIndex),
                "header": {"revision": int(raw.header.revision),
                           "member_id": int(raw.header.member_id)}}

    async def compact(self, rev: int, physical: bool = True) -> None:
        await self._call("compact", pb.CompactionRequest(
            revision=int(rev), physical=physical))

    async def defrag(self) -> None:
        await self._call("defragment", pb.DefragmentRequest())

    # await_node_ready: the base Client implementation works unchanged
    # through the overridden status()
