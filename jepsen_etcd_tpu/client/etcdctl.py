"""The etcdctl-style text client backend.

The reference keeps a second client that SSHes to a node and drives the
``etcdctl`` binary with a *textual* txn syntax, proving clients are
swappable behind the 1-method seam (``client/etcdctl.clj``, seam at
``client/support.clj:4-6``). We preserve that seam: this backend compiles
the txn AST to etcdctl's text format (``txn->text``,
client/etcdctl.clj:125-165 — note the inverted comparison syntax
``mod("k") < 5``), round-trips it through a parser (the "binary"), and
only then executes — so a compiler/parser bug surfaces exactly like an
etcdctl incompatibility would. Values cross the text boundary as JSON
(the analog of the base64+EDN re-reading at client/etcdctl.clj:73-123).

Per-client command logs mirror the reference's per-client log files
(client/etcdctl.clj:175-196, stored via store/path!).
"""

from __future__ import annotations

import json
from typing import Any

from .base import Client
from ..sut.store import Txn
from ..sut.errors import SimError


def _enc(v: Any) -> str:
    return json.dumps(v, sort_keys=True, default=repr)


def _dec(s: str) -> Any:
    return json.loads(s)


TARGET_FNS = {"version": "ver", "value": "val", "mod_revision": "mod",
              "create_revision": "create"}
FN_TARGETS = {v: k for k, v in TARGET_FNS.items()}


def txn_to_text(txn: Txn) -> str:
    """Serialize a server-shape Txn to etcdctl's interactive txn format."""
    lines = ["compares:"]
    for (op, key, target, operand) in txn.cmps:
        fn = TARGET_FNS[target]
        lines.append(f'{fn}("{key}") {op} {_enc(operand)}')
    lines.append("")
    lines.append("success requests:")
    for o in txn.then_ops:
        lines.append(_op_text(o))
    lines.append("")
    lines.append("failure requests:")
    for o in txn.else_ops:
        lines.append(_op_text(o))
    lines.append("")
    return "\n".join(lines)


def _op_text(o: tuple) -> str:
    if o[0] == "get":
        return f'get "{o[1]}"'
    if o[0] == "put":
        lease = f" --lease={o[3]:x}" if len(o) > 3 and o[3] else ""
        return f'put "{o[1]}" {_enc(o[2])}{lease}'
    if o[0] == "delete":
        return f'del "{o[1]}"'
    raise ValueError(f"cannot serialize op {o!r}")


def text_to_txn(text: str) -> Txn:
    """Parse the etcdctl txn text back into the server shape."""
    section = None
    cmps: list = []
    then_ops: list = []
    else_ops: list = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line == "compares:":
            section = "cmp"
            continue
        if line == "success requests:":
            section = "then"
            continue
        if line == "failure requests:":
            section = "else"
            continue
        if section == "cmp":
            cmps.append(_parse_cmp(line))
        elif section in ("then", "else"):
            target = then_ops if section == "then" else else_ops
            target.append(_parse_op(line))
        else:
            raise SimError("unavailable", f"etcdctl parse error: {line!r}",
                           definite=True)
    return Txn(tuple(cmps), tuple(then_ops), tuple(else_ops))


def _parse_cmp(line: str) -> tuple:
    # e.g.: mod("key") = 5
    fn, rest = line.split("(", 1)
    key_part, rest = rest.split(")", 1)
    key = json.loads(key_part)
    rest = rest.strip()
    op = rest[0]
    operand = _dec(rest[1:].strip())
    return (op, key, FN_TARGETS[fn.strip()], operand)


def _parse_op(line: str) -> tuple:
    parts = line.split(None, 1)
    kind = parts[0]
    rest = parts[1] if len(parts) > 1 else ""
    if kind == "get":
        return ("get", json.loads(rest))
    if kind == "del":
        return ("delete", json.loads(rest))
    if kind == "put":
        lease = 0
        if " --lease=" in rest:
            rest, lease_s = rest.rsplit(" --lease=", 1)
            lease = int(lease_s, 16)
        # key is the first JSON string; value is the remainder
        decoder = json.JSONDecoder()
        key, at = decoder.raw_decode(rest)
        value = _dec(rest[at:].strip())
        return ("put", key, value, lease)
    raise ValueError(f"cannot parse op line {line!r}")


class EtcdctlClient(Client):
    """Txn-only text backend (like the reference's etcdctl client, which
    implements only the txn seam, client/etcdctl.clj:170-217)."""

    def __init__(self, cluster, node):
        super().__init__(cluster, node)
        self.log: list[str] = []  # per-client command log

    async def _txn_rpc(self, txn: Txn) -> dict:
        text = txn_to_text(txn)
        self.log.append(text)
        parsed = text_to_txn(text)
        # values crossed a JSON boundary; results come back as JSON types
        raw = await self._call(self.cluster.kv_txn(self.node, parsed))
        self.log.append(json.dumps({"succeeded": raw["succeeded"],
                                    "revision": raw["revision"]}))
        return raw
