"""Constructors for the little transaction AST.

Mirrors the reference txn language (``client/txn.clj``): ops are
``("get", k)`` / ``("put", k, v)``; comparison targets are
``("version", v)``, ``("value", v)``, ``("mod-revision", r)``,
``("create-revision", r)``; comparisons are ``("=", k, target)``,
``("<", k, target)``, (">", k, target)``.

Workloads build guards exactly like the reference does, e.g. the append
workload's optimistic-txn guards (append.clj:85-97):

    eq(k, mod_revision(rev))     # key unchanged since read
    lt(k, mod_revision(rev + 1)) # key still absent (mod-rev 0 < read rev+1)
"""

from __future__ import annotations

from typing import Any


def get(k: str) -> tuple:
    return ("get", k)


def put(k: str, v: Any) -> tuple:
    return ("put", k, v)


def delete(k: str) -> tuple:
    return ("delete", k)


def version(v: int) -> tuple:
    return ("version", v)


def value(v: Any) -> tuple:
    return ("value", v)


def mod_revision(r: int) -> tuple:
    return ("mod-revision", r)


def create_revision(r: int) -> tuple:
    return ("create-revision", r)


def eq(k: str, target: tuple) -> tuple:
    return ("=", k, target)


def lt(k: str, target: tuple) -> tuple:
    return ("<", k, target)


def gt(k: str, target: tuple) -> tuple:
    return (">", k, target)
