"""Client-side error classification: the with-errors seam.

Mirrors ``client.clj:388-399``: a definite error (or an indefinite error on
an idempotent op) fails the op (:fail — it certainly didn't happen / can't
matter); anything else is :info (unknown outcome, the op may have taken
effect). The error taxonomy itself lives in sut/errors.py, preserving the
reference's remap-errors keywords (client.clj:279-379).
"""

from __future__ import annotations

from typing import Awaitable, Callable, Iterable

from ..core.op import Op
from ..sut.errors import SimError
from ..runner.sim import Cancelled


def client_error(e: BaseException) -> bool:
    return isinstance(e, (SimError, TimeoutError))


async def with_errors(op: Op, idempotent: Iterable[str],
                      thunk: Callable[[], Awaitable[Op]]) -> Op:
    """Run thunk; convert known errors to :fail / :info completions."""
    try:
        return await thunk()
    except TimeoutError:
        e = SimError("timeout", "client timeout")
        t = "fail" if op.get("f") in idempotent else "info"
        return op.evolve(type=t, error=e.as_error_value())
    except SimError as e:
        t = "fail" if (e.definite or op.get("f") in idempotent) else "info"
        return op.evolve(type=t, error=e.as_error_value())
    except Cancelled:
        raise


def remap_etcd_message(msg: str):
    """etcd hides specific conditions under generic gRPC codes
    (client.clj:302-353); both live adapters must remap by message
    text FIRST, identically, or the same server fault would classify
    differently per --client-type. Returns a SimError or None."""
    low = msg.lower()
    if "leader changed" in low:
        return SimError("leader-changed", msg)
    if "raft: stopped" in low:
        return SimError("raft-stopped", msg)
    if "lease not found" in low:
        return SimError("lease-not-found", msg)
    if "compacted" in low:
        return SimError("compacted", msg)
    return None
