"""The client protocol: the full KV/txn/lease/lock/watch/cluster surface.

Re-designs the jetcd façade (``client.clj``) as an async Python protocol.
The one polymorphic seam — ``txn(cmps, then_ops, else_ops)`` — mirrors the
reference's single-method Client protocol (``client/support.clj:4-6``),
which is what lets the direct and etcdctl-style backends interchange.

All calls apply the 5 s client timeout (``client.clj:70-72``); timeouts
surface as indefinite errors.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..runner.sim import current_loop, wait_for, sleep, SECOND
from ..sut.cluster import Cluster
from ..sut.errors import SimError
from ..sut.store import Txn

TIMEOUT = 5 * SECOND  # reference: 5000ms


def compile_txn(cmps: list, then_ops: list, else_ops: list) -> Txn:
    """Compile the client AST into the server Txn shape (the analog of
    txn->java, client.clj:700-721)."""
    ccmps = []
    for c in cmps or []:
        op, key, (target, operand) = c
        ccmps.append((op, key, target.replace("-", "_"), operand))
    def comp_ops(ops):
        out = []
        for o in ops or []:
            if o[0] == "get":
                out.append(("get", o[1]))
            elif o[0] == "put":
                out.append(("put", o[1], o[2], o[3] if len(o) > 3 else 0))
            elif o[0] == "delete":
                out.append(("delete", o[1]))
            else:
                raise ValueError(f"unknown txn op {o!r}")
        return out
    return Txn(tuple(ccmps), tuple(comp_ops(then_ops)),
               tuple(comp_ops(else_ops)))


def txn_result(raw: dict) -> dict:
    """Convert a server txn result into the client shape (the analog of
    the ToClj conversions + result zipping, client.clj:723-750)."""
    gets = [r[1] for r in raw["results"] if r[0] == "get"]
    puts = [{"prev-kv": r[1]} for r in raw["results"] if r[0] == "put"]
    return {
        "succeeded": raw["succeeded"],
        "results": raw["results"],
        "gets": gets,
        "puts": puts,
        "header": {"revision": raw["revision"]},
    }


class Client:
    """Base client; subclasses implement _txn_rpc (the backend seam)."""

    def __init__(self, cluster: Cluster, node: str):
        self.cluster = cluster
        self.node = node
        self.open = True

    # ---- plumbing ---------------------------------------------------------

    async def _call(self, coro, timeout: int = TIMEOUT) -> Any:
        """Issue an RPC with the client timeout."""
        if not self.open:
            coro.close()  # silence "never awaited" — arg already built
            raise SimError("closed-client", self.node)
        loop = current_loop()
        if self.cluster.tracer is not None:
            method = getattr(coro, "__qualname__", "rpc").split(".")[-1]
            self.cluster.tracer.record("client-rpc", "client", self.node,
                                       method=method)
        task = loop.spawn(coro, name=f"rpc-{self.node}")
        return await wait_for(task, timeout)

    async def _txn_rpc(self, txn: Txn) -> dict:
        raise NotImplementedError

    def close(self) -> None:
        self.open = False

    # ---- txn seam (support.clj Client protocol) ---------------------------

    async def txn(self, cmps: list, then_ops: list,
                  else_ops: list = None) -> dict:
        """If/Then/Else transaction (client.clj:464-485)."""
        t = compile_txn(cmps or [], then_ops or [], else_ops or [])
        raw = await self._txn_rpc(t)
        return txn_result(raw)

    # ---- KV sugar (client.clj:405-527) ------------------------------------

    async def get(self, k: str, serializable: bool = False) -> Optional[dict]:
        """Read one key; returns the kv map or None (client.clj:432-462)."""
        out = await self._call(self.cluster.kv_read(
            self.node, k, serializable=serializable))
        return out["kv"]

    async def put(self, k: str, v: Any) -> dict:
        """Write, returning prev-kv (client.clj:424-430)."""
        res = await self.txn([], [("put", k, v)])
        return res["puts"][0] | {"header": res["header"]}

    async def cas(self, k: str, old: Any, new: Any) -> dict:
        """Value compare-and-set (cas*!, client.clj:487-492)."""
        from . import txn as t
        return await self.txn([t.eq(k, t.value(old))], [t.put(k, new)])

    async def cas_revision(self, k: str, rev: int, new: Any) -> dict:
        """Mod-revision CAS (client.clj:502-509)."""
        from . import txn as t
        return await self.txn([t.eq(k, t.mod_revision(rev))], [t.put(k, new)])

    async def swap(self, k: str, f: Callable[[Any], Any]) -> Any:
        """CAS retry loop with random <=50ms backoff (client.clj:511-527).

        Returns the new value. Reads use linearizable gets; absent keys
        CAS on version 0.
        """
        from . import txn as t
        loop = current_loop()
        while True:
            cur = await self.get(k)
            if cur is None:
                new = f(None)
                res = await self.txn([t.eq(k, t.version(0))],
                                     [t.put(k, new)])
            else:
                new = f(cur["value"])
                res = await self.txn(
                    [t.eq(k, t.mod_revision(cur["mod-revision"]))],
                    [t.put(k, new)])
            if res["succeeded"]:
                return new
            await sleep(loop.rng.randint(0, 50_000_000))

    async def revision(self) -> int:
        """Current cluster revision (client.clj:695-698)."""
        out = await self._call(self.cluster.kv_read(self.node, "\x00"))
        return out["revision"]

    # ---- leases (client.clj:529-554) --------------------------------------

    async def lease_grant(self, ttl_ns: int) -> int:
        return await self._call(self.cluster.lease_grant(self.node, ttl_ns))

    async def lease_revoke(self, lease_id: int) -> None:
        await self._call(self.cluster.lease_revoke(self.node, lease_id))

    async def lease_keepalive_once(self, lease_id: int) -> int:
        return await self._call(
            self.cluster.lease_keepalive(self.node, lease_id))

    def spawn_keepalive(self, lease_id: int, interval_ns: int):
        """Background keepalive stream (client.clj:544-554 StreamObserver);
        returns the task — cancel it to stop."""
        loop = current_loop()

        async def pump():
            while True:
                await sleep(interval_ns)
                try:
                    await self.lease_keepalive_once(lease_id)
                except (SimError, TimeoutError):
                    return  # stream broken

        return loop.spawn(pump(), name=f"keepalive-{lease_id:x}")

    # ---- locks (client.clj:556-569) ---------------------------------------

    async def acquire_lock(self, name: str, lease_id: int,
                           timeout: int = TIMEOUT) -> str:
        return await self._call(
            self.cluster.lock(self.node, name, lease_id), timeout)

    async def release_lock(self, lock_key: str) -> None:
        await self._call(self.cluster.unlock(self.node, lock_key))

    # ---- watch (client.clj:663-693) ---------------------------------------

    def watch(self, k: str, from_revision: int,
              on_events: Callable, on_error: Callable):
        """Open a watch stream from a revision; returns a cancelable."""
        return self.cluster.watch(self.node, k, from_revision,
                                  on_events, on_error)

    # ---- membership (client.clj:571-636) ----------------------------------

    async def member_list(self) -> list[dict]:
        """Member maps {id, name, peer-urls, client-urls}
        (list-members, client.clj:571-579)."""
        return await self._call(self.cluster.member_list(self.node))

    async def member_id_of_node(self, node: str) -> int:
        """node name -> member id (member-id-of-node, client.clj:581-595);
        raises if the node is not a member."""
        for m in await self.member_list():
            if m["name"] == node:
                return m["id"]
        raise SimError("member-not-found", node)

    async def node_of_member_id(self, member_id: int) -> str:
        """member id -> node name (node-of-member-id, client.clj:597-613);
        raises if no member has that id."""
        for m in await self.member_list():
            if m["id"] == member_id:
                return m["name"]
        raise SimError("member-not-found", hex(member_id))

    async def add_member(self, name: str) -> None:
        await self._call(self.cluster.member_add(self.node, name))

    async def remove_member(self, name: str) -> None:
        await self._call(self.cluster.member_remove(self.node, name))

    async def remove_member_by_id(self, member_id: int) -> None:
        """Remove by id like the reference's remove-member!
        (client.clj:624-636 resolves the id first)."""
        await self.remove_member(await self.node_of_member_id(member_id))

    # ---- maintenance (client.clj:638-661) ---------------------------------

    async def status(self) -> dict:
        return await self._call(self.cluster.status(self.node))

    async def compact(self, rev: int, physical: bool = True) -> None:
        await self._call(self.cluster.compact(self.node, rev, physical))

    async def defrag(self) -> None:
        await self._call(self.cluster.defrag(self.node))

    async def await_node_ready(self, max_tries: int = 20) -> bool:
        """Retry status until the node reports a leader
        (client.clj:652-661)."""
        for _ in range(max_tries):
            try:
                st = await self.status()
                if st.get("leader") is not None or st.get("is-leader"):
                    return True
            except (SimError, TimeoutError):
                pass
            await sleep(1 * SECOND)
        raise SimError("unavailable",
                       f"node {self.node} never became ready")
