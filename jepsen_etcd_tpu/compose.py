"""Test composition: the etcd-test analog (etcd.clj:90-155).

Builds a full test map from CLI-style opts: workload + db + nemesis
package + the phased generator (main phase at :rate under the nemesis,
then heal, recover, final client generator) + the composed checker stack.
"""

from __future__ import annotations

from typing import Any, Optional

from .core.op import NEMESIS
from .generators import (phases, stagger, time_limit, nemesis as gen_nemesis,
                         clients as gen_clients, log as gen_log, sleep_gen)
from .workloads import workloads
from .checkers import (compose as compose_checkers, Stats,
                       UnhandledExceptions, LogFilePattern, ClockPlot,
                       Perf, TimelineHtml)
from .db import db as make_db
from .nemesis import nemesis_package
from .runner.sim import SECOND

DEFAULT_NODES = ["n1", "n2", "n3", "n4", "n5"]


def default_opts() -> dict:
    """CLI defaults mirroring cli-opts (etcd.clj:157-209)."""
    return {
        "nodes": list(DEFAULT_NODES),
        "workload": "register",
        "nemesis": [],                  # e.g. ["kill", "partition"]
        "nemesis_interval": 5,          # seconds (etcd.clj:177-180)
        "rate": 200.0,                  # hz (etcd.clj:190-193)
        "ops_per_key": 200,             # etcd.clj:182-185
        "time_limit": 30,               # seconds
        "concurrency": None,            # default 2n
        "serializable": False,
        "lazyfs": False,
        "client_type": "direct",        # or "etcdctl" (etcd.clj:161-164)
        "db_mode": None,                # sim | live | local (None: infer
                                        # from client_type)
        "etcd_binary": None,            # --db local: argv prefix; None =
                                        # etcd from PATH, else fake stub
        "etcd_data_dir": None,          # --db local: data/log root
        "etcd_env": None,               # --db local: extra child env
        "snapshot_count": 100,          # etcd.clj:197-200
        "unsafe_no_fsync": False,       # etcd.clj:204 (opt-in, like etcd)
        "corrupt_check": False,         # etcd.clj:164
        "seed": 0,
        "debug": False,
        "no_telemetry": False,          # every run writes telemetry.jsonl
                                        # unless opted out (--no-telemetry)
        "stream": False,                # --stream: online chunked checking
                                        # (runner/stream.py) overlapped
                                        # with generation
        "stream_chunk_ops": 1024,       # ops per streamed chunk
        "key_offset": 0,                # first register key id (soak
                                        # windows rotate it so a retained
                                        # cluster never re-serves a
                                        # checked key)
        "soak": False,                  # --soak: sliding-window run
                                        # against one long-lived cluster
        "soak_windows": 0,              # 0 = run until interrupted
        "soak_window_s": None,          # per-window time limit (None:
                                        # --time-limit)
        "soak_net_faults": [],          # --soak-net-fault schedule:
                                        # windows cycle [healthy]+these,
                                        # each held for a whole window
                                        # on the proxy plane
        "version": "sim-3.5.6",         # etcd.clj:206-207 (pinned: the sim
                                        # has exactly one "binary")
        "checker_service": None,        # AF_UNIX socket path or
                                        # tcp://HOST:PORT endpoint of a
                                        # campaign checker service
                                        # (runner/checker_service.py);
                                        # None = check in-process. Env
                                        # JEPSEN_ETCD_TPU_CHECKER_SERVICE
                                        # is the fallback source.
        "checker_service_token": None,  # shared-secret auth token for
                                        # a TCP checker service (env
                                        # JEPSEN_ETCD_TPU_SERVICE_TOKEN
                                        # is the fallback source)
        "host_id": None,                # this run's generator-host
                                        # name: stamps the JET-HOST
                                        # preamble + the service's
                                        # service.host_submitted.*
                                        # ledger (campaign --hosts
                                        # sets it per agent; env
                                        # JEPSEN_ETCD_TPU_HOST is the
                                        # fallback source)
        "force_kernel": False,          # disable the native-DFS size
                                        # cutoff so every key is
                                        # device-bound (campaign
                                        # coalescing tests/bench on CPU;
                                        # production leaves the measured
                                        # routing alone)
        "net_proxy": False,             # --db local: front every peer/
                                        # client URL with the userspace
                                        # proxy plane (net/plane.py).
                                        # Auto-set when partition or
                                        # latency faults are requested.
        "gen_epoch": "epoch-v1",        # generator epoch (see the epoch
                                        # ledger in runner/sim.py):
                                        # epoch-v1 = SimLoop event loop;
                                        # epoch-v2 routes campaign sim
                                        # runs through the batched
                                        # lockstep generator (simbatch/)
        "inject_stale_reads": False,    # seed a stale-read serving bug
                                        # in the sim (guided-campaign
                                        # quarry; with nemeses present
                                        # it only fires inside open
                                        # partition windows)
        "nem_schedule": None,           # explicit nemesis schedule:
                                        # [[start_ns, kind, hold_ns],
                                        # ...] replayed verbatim instead
                                        # of the drawn fault plan
                                        # (shrink repros, guided window
                                        # mutations)
        "nem_partition_shape": None,    # partition grudge override
                                        # (majority | primaries | ...);
                                        # None keeps the drawn shape
        "nem_latency_ms": None,         # latency-fault delta override
                                        # in ms; scales the latency
                                        # window timeout probability
        "nem_drop_prob": 0.0,           # extra flat drop probability
                                        # added inside every open fault
                                        # window
        "staleness_bound_s": 8.0,       # register-stale: max excusable
                                        # read lag (virtual seconds)
                                        # without an open fault window
        "lease_ttl_ms": 1500,           # lock-lease: never-renewed
                                        # lease TTL (churn pressure)
        "compact_keep": 8,              # compact-watch: revisions kept
                                        # behind the head per compaction
        "inject_stale_snapshot": False,  # MVCC injection hooks
        "inject_torn_range": False,      # (simbatch/engine.py): each
        "inject_double_grant": False,    # seeds the one bug its
        "inject_compaction_swallow": False,  # checker class pins
    }


#: faults the local control plane (db/local.py) can inject with plain
#: process-level privileges; partition + latency ride the userspace
#: TCP proxy plane (net/plane.py), raised automatically when requested
LOCAL_FAULTS = {"kill", "pause", "member", "admin", "partition",
                "latency"}

#: fault -> why `--db local` refuses it (each REMAINING failure mode is
#: specific and documented, not a blanket live-mode error; see README
#: "Fault / privilege matrix". Partition/latency used to live here —
#: the net proxy plane closed that gap.)
LOCAL_FAULT_REFUSALS = {
    "clock": ("clock skew needs per-process time virtualization "
              "(CAP_SYS_TIME / libfaketime); the local control plane "
              "does not alter the host clock"),
    "bitflip-wal": ("on-disk corruption injection targets the "
                    "simulated WAL/snapshot files; a real etcd's data "
                    "dir has no byte-level corruption hook here"),
}
LOCAL_FAULT_REFUSALS["bitflip-snap"] = LOCAL_FAULT_REFUSALS["bitflip-wal"]
LOCAL_FAULT_REFUSALS["truncate-wal"] = LOCAL_FAULT_REFUSALS["bitflip-wal"]


def fault_matrix(db_mode: str = "local") -> dict:
    """fault -> {"supported": bool, "why": refusal-or-None} for the
    given db mode; the README table and test_config_plane assert these
    rows. Sim supports everything; live supports nothing (the cluster
    is external)."""
    from .nemesis.faults import KNOWN_FAULTS
    rows = {}
    for fault in sorted(KNOWN_FAULTS):
        if db_mode == "live":
            supported, why = False, "external cluster: no control plane"
        elif db_mode == "local":
            supported = fault in LOCAL_FAULTS
            why = None if supported else LOCAL_FAULT_REFUSALS.get(
                fault, "not implemented")
        else:
            supported, why = True, None
        rows[fault] = {"supported": supported, "why": why}
    return rows


def _check_fault_support(db_mode: str, o: dict) -> None:
    """Refuse unsupportable fault requests up front, specifically."""
    faults = list(o.get("nemesis") or [])
    if not faults:
        return
    if db_mode == "live":
        # the reference faults real nodes over SSH (db.clj); an
        # external cluster offers only the client wire
        raise ValueError(
            f"live mode (--client-type {o['client_type']}) has no "
            f"control plane for faults {faults}: the cluster is "
            "external. Use --db local to spawn and fault local etcd "
            "processes, or the simulated cluster")
    if db_mode == "local":
        refused = [f for f in faults if f not in LOCAL_FAULTS]
        if refused:
            reasons = "; ".join(
                f"{f}: {LOCAL_FAULT_REFUSALS.get(f, 'not implemented')}"
                for f in sorted(set(refused)))
            raise ValueError(
                f"--db local cannot inject {sorted(set(refused))} — "
                f"{reasons}. Supported local faults: "
                f"{sorted(LOCAL_FAULTS)}")


def etcd_test(opts: dict) -> dict:
    """Compose opts into a runnable test map (etcd-test, etcd.clj:90-155)."""
    o = default_opts()
    o.update(opts or {})
    n = len(o["nodes"])
    if not o.get("concurrency"):
        o["concurrency"] = 2 * n
    wl_fn = workloads()[o["workload"]]
    workload = wl_fn(o)
    live = o["client_type"] in ("http", "grpc")
    db_mode = o.get("db_mode") or ("live" if live else "sim")
    o["db_mode"] = db_mode
    if db_mode in ("live", "local") and not live:
        raise ValueError(
            f"--db {db_mode} drives real etcd over the live wire; use "
            "--client-type http or grpc (direct/etcdctl speak to the "
            "simulated cluster only)")
    if db_mode == "sim" and live:
        raise ValueError(
            f"--client-type {o['client_type']} speaks to real etcd; "
            "--db sim has no live endpoints. Use --db live (external "
            "cluster) or --db local (locally spawned processes)")
    _check_fault_support(db_mode, o)
    if db_mode == "local" and \
            {"partition", "latency"} & set(o.get("nemesis") or []):
        # network faults in local mode ride the userspace proxy plane
        o["net_proxy"] = True
    if db_mode == "local":
        from .db.local import local_db
        o["db"] = local_db(o)
    elif db_mode == "live":
        from .db.live import live_db
        o["db"] = live_db(o)
    else:
        o["db"] = make_db(o)
    nem = nemesis_package(o)

    rate_gap = int(SECOND / o["rate"]) if o["rate"] else 0
    main_gen = workload["generator"]
    if rate_gap:
        main_gen = stagger(rate_gap, main_gen)
    main_phase = time_limit(
        int(o["time_limit"] * SECOND),
        gen_nemesis(
            phases(sleep_gen(5 * SECOND), nem.get("generator")),
            main_gen))

    phase_list: list = [main_phase]
    if nem.get("generator") is not None or \
            nem.get("final_generator") is not None:
        # heal + 10 s recovery window only when faults actually ran:
        # free in virtual time, but a live run pays it in real seconds
        phase_list.append(gen_log("Healing cluster"))
        if nem.get("final_generator") is not None:
            phase_list.append(gen_nemesis(nem["final_generator"]))
        phase_list.append(gen_log("Waiting for recovery"))
        phase_list.append(sleep_gen(10 * SECOND))
    if workload.get("final_generator") is not None:
        phase_list.append(gen_clients(workload["final_generator"]))

    checker = compose_checkers({
        "perf": Perf(nemesis_perf=nem.get("perf", [])),
        # top level, not per workload: the full history (nemesis ops
        # included) renders the positioned timeline with fault bands;
        # a per-key subhistory would lose both
        "timeline": TimelineHtml(nemesis_perf=nem.get("perf", [])),
        "clock": ClockPlot(),
        "stats": Stats(),
        "exceptions": UnhandledExceptions(),
        "crash": LogFilePattern(),
        "workload": workload["checker"],
    })

    name = "etcd " + " ".join(
        [o["workload"]] +
        (["sz"] if o["serializable"] else []) +
        (sorted(o["nemesis"]) if o["nemesis"] else []))
    test = dict(o)
    test.update({
        "name": name.replace(" ", "-"),
        # the fault-name list survives here: test["nemesis"] below is
        # the live nemesis OBJECT, which save_run excludes from
        # test.json — the spec is what run reports need
        "nemesis_spec": list(o["nemesis"]),
        "client": workload["client"],
        "generator": phases(*[p for p in phase_list if p is not None]),
        "checker": checker,
        "nemesis": nem.get("nemesis"),
        "nemesis_package": nem,
    })
    return test
