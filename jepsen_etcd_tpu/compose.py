"""Test composition: the etcd-test analog (etcd.clj:90-155).

Builds a full test map from CLI-style opts: workload + db + nemesis
package + the phased generator (main phase at :rate under the nemesis,
then heal, recover, final client generator) + the composed checker stack.
"""

from __future__ import annotations

from typing import Any, Optional

from .core.op import NEMESIS
from .generators import (phases, stagger, time_limit, nemesis as gen_nemesis,
                         clients as gen_clients, log as gen_log, sleep_gen)
from .workloads import workloads
from .checkers import (compose as compose_checkers, Stats,
                       UnhandledExceptions, LogFilePattern, ClockPlot, Perf)
from .db import db as make_db
from .nemesis import nemesis_package
from .runner.sim import SECOND

DEFAULT_NODES = ["n1", "n2", "n3", "n4", "n5"]


def default_opts() -> dict:
    """CLI defaults mirroring cli-opts (etcd.clj:157-209)."""
    return {
        "nodes": list(DEFAULT_NODES),
        "workload": "register",
        "nemesis": [],                  # e.g. ["kill", "partition"]
        "nemesis_interval": 5,          # seconds (etcd.clj:177-180)
        "rate": 200.0,                  # hz (etcd.clj:190-193)
        "ops_per_key": 200,             # etcd.clj:182-185
        "time_limit": 30,               # seconds
        "concurrency": None,            # default 2n
        "serializable": False,
        "lazyfs": False,
        "client_type": "direct",        # or "etcdctl" (etcd.clj:161-164)
        "snapshot_count": 100,          # etcd.clj:197-200
        "unsafe_no_fsync": False,       # etcd.clj:204 (opt-in, like etcd)
        "corrupt_check": False,         # etcd.clj:164
        "seed": 0,
        "debug": False,
        "version": "sim-3.5.6",         # etcd.clj:206-207 (pinned: the sim
                                        # has exactly one "binary")
    }


def etcd_test(opts: dict) -> dict:
    """Compose opts into a runnable test map (etcd-test, etcd.clj:90-155)."""
    o = default_opts()
    o.update(opts or {})
    n = len(o["nodes"])
    if not o.get("concurrency"):
        o["concurrency"] = 2 * n
    wl_fn = workloads()[o["workload"]]
    workload = wl_fn(o)
    live = o["client_type"] in ("http", "grpc")
    if live and o["nemesis"]:
        # the reference faults real nodes over SSH (db.clj); live mode
        # has only the client wire, so faults stay a sim capability
        raise ValueError(
            f"live mode (--client-type {o['client_type']}) has no "
            f"control plane for faults {o['nemesis']}; drop --nemesis "
            "or use the simulated cluster")
    if live:
        from .db.live import live_db
        o["db"] = live_db(o)
    else:
        o["db"] = make_db(o)
    nem = nemesis_package(o)

    rate_gap = int(SECOND / o["rate"]) if o["rate"] else 0
    main_gen = workload["generator"]
    if rate_gap:
        main_gen = stagger(rate_gap, main_gen)
    main_phase = time_limit(
        int(o["time_limit"] * SECOND),
        gen_nemesis(
            phases(sleep_gen(5 * SECOND), nem.get("generator")),
            main_gen))

    phase_list: list = [main_phase]
    if nem.get("generator") is not None or \
            nem.get("final_generator") is not None:
        # heal + 10 s recovery window only when faults actually ran:
        # free in virtual time, but a live run pays it in real seconds
        phase_list.append(gen_log("Healing cluster"))
        if nem.get("final_generator") is not None:
            phase_list.append(gen_nemesis(nem["final_generator"]))
        phase_list.append(gen_log("Waiting for recovery"))
        phase_list.append(sleep_gen(10 * SECOND))
    if workload.get("final_generator") is not None:
        phase_list.append(gen_clients(workload["final_generator"]))

    checker = compose_checkers({
        "perf": Perf(nemesis_perf=nem.get("perf", [])),
        "clock": ClockPlot(),
        "stats": Stats(),
        "exceptions": UnhandledExceptions(),
        "crash": LogFilePattern(),
        "workload": workload["checker"],
    })

    name = "etcd " + " ".join(
        [o["workload"]] +
        (["sz"] if o["serializable"] else []) +
        (sorted(o["nemesis"]) if o["nemesis"] else []))
    test = dict(o)
    test.update({
        "name": name.replace(" ", "-"),
        # the fault-name list survives here: test["nemesis"] below is
        # the live nemesis OBJECT, which save_run excludes from
        # test.json — the spec is what run reports need
        "nemesis_spec": list(o["nemesis"]),
        "client": workload["client"],
        "generator": phases(*[p for p in phase_list if p is not None]),
        "checker": checker,
        "nemesis": nem.get("nemesis"),
        "nemesis_package": nem,
    })
    return test
