"""The TPU kernels: history analysis as JAX programs.

- wgl: linearizability search as windowed-bitmask frontier BFS
- closure: boolean-matmul transitive closure / SCC for Elle
- edit_distance: anti-diagonal wavefront DP for the watch checker
"""
