"""Fused Pallas wave kernel for the WGL frontier BFS (info-free fast path).

The jnp wave loop (ops/wgl.py:_wgl_loop) costs ~100 us/wave on a v5e:
each wave is ~a hundred small XLA ops on KB-sized tensors, so per-op
dispatch dominates — the history check is latency-bound, not
compute-bound. This kernel fuses one whole wave into a single Pallas
grid step with all state resident in VMEM.

The structural win that makes this simple: with NO info ops, every
frontier state at wave k sits at depth exactly k (each successor
advances depth by one, the initial state is depth 0). So the grid IS
the wave counter, one row of each per-depth table streams into VMEM
per step via BlockSpecs (double-buffered by the pipeline), and the
frontier is a handful of (32, 128) vregs:

- ``st_w``/``st_v``: window bitmask and value id per state, one state
  per sublane row, replicated across lanes so candidate generation
  (bit = 1 << lane) is pure elementwise math;
- dedupe/compaction is a greedy select loop: pick any remaining valid
  candidate, broadcast it into the next frontier row, kill its
  duplicates — no sort, no cross-lane shuffles (frontier order is
  irrelevant to BFS correctness);
- acceptance, overflow, frontier size and peak live in SMEM scratch;
  steps after termination are @pl.when-guarded no-ops.

Scope (preconditions checked by ``supported``): W <= 32 window (one
mask word), no info ops, frontier capacity 32. Overflow (more than 32
distinct successors) bails out; the caller falls back to the complete
jnp capacity ladder. Soundness contract is the kernel's: definitive
answers only, never a wrong verdict — differentially fuzzed against
the jnp kernel and both CPU oracles in tests/test_wgl_pallas.py.

Reference role: this is the hot path of the Knossos-equivalent checker
(register.clj:110-112); the reference has no analog (Knossos is a JVM
heap search).
"""

from __future__ import annotations

import functools

import numpy as np

from .wgl import (CAS, NO_ASSERT, NONE_VAL, READ, WILDCARD, WRITE,
                  Packed, bucket, pad_tables)

F = 32          # frontier capacity (sublane rows of one state block)
PICK_CHUNK = 4  # branchless picks per scalar-guarded chunk
LANES = 32      # lane width = window width (blocks use the exact array
                # width, so tables ship unpadded: 4x less host prep and
                # host->device traffic than 128-lane padding)
CEIL_INF = 2 ** 30
BIG = np.int32(2 ** 31 - 1)

# out vector layout (SMEM (1, 8) int32)
O_ACCEPTED, O_OVERFLOW, O_WAVES, O_PEAK, O_N = 0, 1, 2, 3, 4
# smem scratch layout
S_N, S_DONE, S_ACC, S_OVF, S_PEAK, S_WAVES, S_MORE, S_CNT = range(8)


def supported(p: Packed) -> bool:
    """This kernel's preconditions: packed OK, one mask word, no info
    ops (the depth==wave invariant), and register-style codes."""
    return bool(p.ok) and p.w == 32 and p.I == 0 and p.R > 0


def _kernel(rt_ref, sok_ref, fc_ref, a1_ref, a2_ref, ver_ref, pred_ref,
            ceil_ref, scal_ref, out_ref, st_w, st_v, val_s, nw_s, nv_s,
            sm):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    k = pl.program_id(0)
    R = rt_ref[0, 0]
    # table blocks hold 8 consecutive depth rows (TPU block-shape
    # minimum); the pipeline skips the re-fetch while k//8 is unchanged
    sub = k % 8
    trow = lambda ref: ref[pl.ds(sub, 1), :]        # (1,L) depth row

    @pl.when(k == 0)
    def _init():
        st_w[:] = jnp.zeros((F, LANES), jnp.uint32)
        st_v[:] = jnp.full((F, LANES), NONE_VAL, jnp.int32)
        sm[S_N] = 1
        sm[S_DONE] = jnp.where(R == 0, 1, 0)
        sm[S_ACC] = jnp.where(R == 0, 1, 0)
        sm[S_OVF] = 0
        sm[S_PEAK] = 1
        sm[S_WAVES] = 0

    run = (sm[S_DONE] == 0) & (k < R)

    @pl.when(run)
    def _wave():
        lane = jax.lax.broadcasted_iota(jnp.int32, (F, LANES), 1)
        srow = jax.lax.broadcasted_iota(jnp.int32, (F, LANES), 0)
        lsh = lane.astype(jnp.uint32)       # every lane is a real op slot

        w = st_w[:]
        v = st_v[:]
        n = sm[S_N]
        alive = srow < n

        shift = scal_ref[sub, 0]
        u_forced = scal_ref[sub, 1]
        ceil_beyond = scal_ref[sub, 2]
        upd = scal_ref[sub, 3]  # uint32 mask bit-identical in int32

        s_ok = trow(sok_ref) != 0                   # (1,L) -> bcast
        fc = trow(fc_ref)
        a1 = trow(a1_ref)
        a2 = trow(a2_ref)
        rver = trow(ver_ref)
        pred = trow(pred_ref).astype(jnp.uint32)
        ceil_row = trow(ceil_ref)

        not_set = ((w >> lsh) & jnp.uint32(1)) == 0
        preds_in = (w & pred) == pred
        version = (u_forced
                   + lax.population_count(
                       w & jnp.uint32(upd)).astype(jnp.int32))
        # version-ceiling prune
        ceil_cand = jnp.where(not_set, ceil_row, CEIL_INF)
        min_ceil = jnp.minimum(
            jnp.min(ceil_cand, axis=1, keepdims=True), ceil_beyond)
        alive = alive & (version <= min_ceil)

        is_read = fc == READ
        is_write = fc == WRITE
        is_cas = fc == CAS
        no_assert = rver == NO_ASSERT
        # boolean algebra, not where(): i1 selects don't lower on TPU
        ver_ok = no_assert | (is_read & (rver == version)) | \
            (~is_read & (rver == version + 1))
        read_ok = is_read & ((a1 == WILDCARD) | (a1 == v))
        model_ok = read_ok | is_write | (is_cas & (a1 == v))

        bitb = jnp.uint32(1) << lsh
        new_w_full = w | bitb
        # slide: the `shift` lowest bits fall off and must all be set
        ssafe = jnp.minimum(shift, 31).astype(jnp.uint32)
        low = jnp.where(shift >= 32, jnp.uint32(0xFFFFFFFF),
                        (jnp.uint32(1) << ssafe) - jnp.uint32(1))
        slide_ok = (new_w_full & low) == low
        new_w = jnp.where(shift >= 32, jnp.uint32(0),
                          new_w_full >> ssafe)

        valid = (alive & s_ok & not_set & preds_in
                 & ver_ok & model_ok & slide_ok)
        new_v = jnp.where(is_read, v,
                          jnp.where(is_write, a1, a2)).astype(jnp.int32)

        accepted = jnp.any(valid) & (k + 1 == R)

        # greedy dedupe -> next frontier (order-free: BFS doesn't care)
        code = srow * LANES + lane

        # reductions over uint32 are unsupported in Mosaic: select in
        # int32 bit-space and convert back
        new_w_bits = lax.bitcast_convert_type(new_w, jnp.int32)

        # statically unrolled (Mosaic won't legalize an scf.for with
        # vreg carries) in chunks of PICK_CHUNK branchless picks: the
        # old one-@pl.when-per-pick form paid a vector->scalar sync
        # (any() -> SMEM -> scf.if) per pick, ~3/4 of the wave cost.
        # Within a chunk everything stays in vregs — an exhausted pick
        # selects nothing (idx == BIG -> put mask empty) and is a cheap
        # vector no-op; the per-chunk guard still skips the tail, so
        # typical waves (a handful of distinct successors) run one
        # chunk and two scalar syncs total.
        val_s[:] = valid.astype(jnp.int32)
        nw_s[:] = jnp.zeros((F, LANES), jnp.uint32)
        nv_s[:] = jnp.zeros((F, LANES), jnp.int32)
        sm[S_CNT] = 0
        sm[S_MORE] = jnp.any(valid).astype(jnp.int32)
        for c in range(0, F, PICK_CHUNK):
            @pl.when(sm[S_MORE] == 1)
            def _chunk(c=c):
                val = val_s[:] != 0
                nw_c = nw_s[:]
                nv_c = nv_s[:]
                cnt = jnp.int32(0)
                for i in range(c, c + PICK_CHUNK):
                    idx = jnp.min(jnp.where(val, code, BIG))
                    sel = code == idx
                    # int32 -> uint32 astype wraps mod 2^32:
                    # bit-identical, and scalar-legal where a scalar
                    # bitcast is not
                    w_sel = jnp.sum(jnp.where(sel, new_w_bits, 0)) \
                        .astype(jnp.uint32)
                    v_sel = jnp.sum(jnp.where(sel, new_v, 0))
                    has = idx < BIG
                    put = (srow == i) & has
                    nw_c = jnp.where(put, w_sel, nw_c)
                    nv_c = jnp.where(put, v_sel, nv_c)
                    cnt = cnt + has.astype(jnp.int32)
                    val = val & ~((new_w == w_sel) & (new_v == v_sel))
                nw_s[:] = nw_c
                nv_s[:] = nv_c
                val_s[:] = val.astype(jnp.int32)
                sm[S_CNT] = sm[S_CNT] + cnt
                sm[S_MORE] = jnp.any(val).astype(jnp.int32)
        cnt = sm[S_CNT]
        overflow = (sm[S_MORE] == 1) & ~accepted

        st_w[:] = nw_s[:]
        st_v[:] = nv_s[:]
        sm[S_N] = cnt
        sm[S_WAVES] = k + 1
        sm[S_PEAK] = jnp.maximum(sm[S_PEAK], cnt)
        sm[S_ACC] = jnp.maximum(sm[S_ACC], accepted.astype(jnp.int32))
        sm[S_OVF] = jnp.maximum(sm[S_OVF], overflow.astype(jnp.int32))
        sm[S_DONE] = jnp.where(
            accepted | overflow | (cnt == 0), 1, sm[S_DONE])

    @pl.when(k == pl.num_programs(0) - 1)
    def _emit():
        out_ref[0, O_ACCEPTED] = sm[S_ACC]
        out_ref[0, O_OVERFLOW] = sm[S_OVF]
        out_ref[0, O_WAVES] = sm[S_WAVES]
        out_ref[0, O_PEAK] = sm[S_PEAK]
        out_ref[0, O_N] = sm[S_N]


@functools.lru_cache(maxsize=None)
def _call(r_pad: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    fixed = pl.BlockSpec((1, 1), lambda k: (0, 0),
                         memory_space=pltpu.SMEM)
    row = lambda width: pl.BlockSpec((8, width), lambda k: (k // 8, 0))
    call = pl.pallas_call(
        _kernel,
        grid=(r_pad,),
        in_specs=[
            fixed,                                   # R_true
            row(LANES), row(LANES), row(LANES),      # s_ok, fc, a1
            row(LANES), row(LANES), row(LANES),      # a2, ver, pred
            row(LANES),                              # ceil_frame
            pl.BlockSpec((8, 4), lambda k: (k // 8, 0),
                         memory_space=pltpu.SMEM),   # per-row scalars
        ],
        out_specs=pl.BlockSpec((1, 8), lambda k: (0, 0),
                               memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((1, 8), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((F, LANES), jnp.uint32),   # st_w
            pltpu.VMEM((F, LANES), jnp.int32),    # st_v
            pltpu.VMEM((F, LANES), jnp.int32),    # val_s (pick mask)
            pltpu.VMEM((F, LANES), jnp.uint32),   # nw_s (next frontier)
            pltpu.VMEM((F, LANES), jnp.int32),    # nv_s
            pltpu.SMEM((8,), jnp.int32),
        ],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )
    return jax.jit(call)


def check_packed_pallas(p: Packed) -> dict | None:
    """Run the fused kernel; None when unsupported, an
    overflow-shaped unknown when capacity 32 was exceeded (caller
    falls back to the jnp ladder)."""
    import jax
    import jax.numpy as jnp

    if not supported(p):
        return None
    r_pad = bucket(p.R)
    t = pad_tables(p, r_pad)
    sok = t["static_ok"].astype(np.int32)
    fc = t["f_code"].astype(np.int32)
    a1 = t["a1"].astype(np.int32)
    a2 = t["a2"].astype(np.int32)
    ver = t["ver"].astype(np.int32)
    pred = np.ascontiguousarray(t["pred_frame"][:, :, 0]).view(np.int32)
    ceil = t["ceil_frame"].astype(np.int32)
    scal = np.stack([
        t["shift"].astype(np.int32),
        t["u_forced"].astype(np.int32),
        t["ceil_beyond"].astype(np.int32),
        t["upd_mask"][:, 0].view(np.int32),
    ], axis=1)
    rt = np.array([[p.R]], dtype=np.int32)

    interpret = jax.default_backend() != "tpu"
    out = np.asarray(_call(r_pad, interpret)(
        jnp.asarray(rt), jnp.asarray(sok), jnp.asarray(fc),
        jnp.asarray(a1), jnp.asarray(a2), jnp.asarray(ver),
        jnp.asarray(pred), jnp.asarray(ceil), jnp.asarray(scal)))[0]
    if out[O_OVERFLOW]:
        return {"valid?": "unknown", "overflow": True,
                "reason": "pallas frontier overflow (capacity 32)",
                "waves": int(out[O_WAVES]),
                "peak-frontier": int(out[O_PEAK])}
    res = {"valid?": bool(out[O_ACCEPTED]),
           "waves": int(out[O_WAVES]),
           "peak-frontier": int(out[O_PEAK]),
           "ops": int(p.R), "info-ops": 0,
           "engine": "pallas-fused"}
    if not res["valid?"]:
        # match the jnp engine's invalid result shape
        res["stuck-at-depth"] = int(out[O_WAVES])
    return res
